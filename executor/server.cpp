// In-sandbox executor server (TPU-native rebuild of the reference's Rust
// executor; behavior parity with executor/server.rs:68-241 — file
// upload/download routes, POST /execute with timeout and changed-file
// detection — re-designed for TPU):
//
//   * Paths are explicitly confined to their base directory (the reference
//     joined attacker-controlled absolute paths, server.rs:83).
//   * User code runs under plain CPython, not xonsh (reclaims the ~80 ms
//     startup acknowledged in server.rs:204) — or, by default, inside a warm
//     persistent runner process that has already imported JAX and initialized
//     the TPU at sandbox boot, so Execute latency excludes libtpu init and
//     device enumeration (seconds on TPU — the pool amortizes it; SURVEY.md §7
//     hard part #2).
//   * Changed-file detection is a recursive mtime+size diff, not the
//     reference's top-level-only ctime scan (server.rs:117-137).
//   * Dependency auto-install uses an AST import scan (deps.py) instead of
//     `upm guess` (server.rs:174-195), gated by APP_AUTO_INSTALL_DEPS.
//
// Env knobs: APP_LISTEN_ADDR (0.0.0.0:8000; port 0 = ephemeral, printed),
// APP_WORKSPACE (/workspace), APP_RUNTIME_PACKAGES (/runtime-packages),
// APP_PYTHON (python3), APP_WARM_RUNNER (1), APP_WARM_EAGER (1; 0 = warm-up
// waits for POST /warmup), APP_RUNNER_READY_TIMEOUT (180), APP_AUTO_INSTALL_DEPS
// (0), APP_DEFAULT_TIMEOUT (60), APP_MAX_OUTPUT_BYTES (10485760),
// APP_WORKSPACE_MANIFEST (1; 0 = legacy wire format: no sha256 manifest,
// plain-string `files` arrays, no /workspace-manifest route).
//
// Resource governance (limits.hpp): APP_LIMIT_MEMORY_BYTES,
// APP_LIMIT_CPU_SECONDS, APP_LIMIT_NPROC, APP_LIMIT_NOFILE,
// APP_LIMIT_FSIZE_BYTES, APP_LIMIT_DISK_BYTES set the server's caps-and-
// defaults (0 = off); a request's `limits` object can only tighten them.
// APP_LIMIT_POLL_INTERVAL (0.1) is the watchdog sampling cadence. Breaches
// kill the runner group and classify as a typed `violation` in the execute
// response (oom / disk_quota / nproc / cpu_time / output_cap) instead of a
// generic crash. The workspace disk quota also guards streaming PUTs (413).

#include <dirent.h>
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <sys/prctl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "cgroup.hpp"
#include "http.hpp"
#include "json.hpp"
#include "limits.hpp"
#include "sha256.hpp"

// Runner session id, mirrored for the SIGTERM handler (async-signal-safe
// cleanup): the runner lives in its own session, so killing the server's
// group misses it, and the runner's own pipe-EOF watchdog cannot run while
// its main thread blocks in GIL-holding native code (e.g. TPU init). The
// server is therefore the one reliable place to reap it on shutdown.
volatile sig_atomic_t g_runner_sid = 0;

extern "C" void handle_shutdown_signal(int) {
  pid_t sid = g_runner_sid;
  if (sid > 0) kill(-sid, SIGKILL);
  _exit(143);
}

namespace {

std::string env_or(const char* name, const std::string& dflt) {
  const char* v = getenv(name);
  return v && *v ? std::string(v) : dflt;
}

double env_num(const char* name, double dflt) {
  const char* v = getenv(name);
  return v && *v ? atof(v) : dflt;
}

bool env_flag(const char* name, bool dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  return strcmp(v, "0") != 0 && strcasecmp(v, "false") != 0;
}

void log_msg(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "[executor] ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
}

// ---------------------------------------------------------------------------
// Path confinement (SURVEY.md §0.4 fix).

// Normalizes a URL path to a safe relative path: strips leading slashes,
// resolves "." segments, rejects "..". Returns empty string on rejection.
std::string sanitize_rel_path(const std::string& raw) {
  std::vector<std::string> parts;
  std::string cur;
  for (size_t i = 0; i <= raw.size(); ++i) {
    char c = i < raw.size() ? raw[i] : '/';
    if (c == '/') {
      if (cur == ".." ) return "";
      if (!cur.empty() && cur != ".") parts.push_back(cur);
      cur.clear();
    } else {
      cur += c;
    }
  }
  if (parts.empty()) return "";
  std::string out;
  for (size_t i = 0; i < parts.size(); ++i) {
    if (i) out += '/';
    out += parts[i];
  }
  return out;
}

// Joins base+rel and verifies the realpath of the existing prefix stays under
// the realpath of base (guards against symlinks planted by user code).
bool confine(const std::string& base, const std::string& rel, std::string& out) {
  char base_real[PATH_MAX];
  if (!realpath(base.c_str(), base_real)) return false;
  std::string candidate = std::string(base_real) + "/" + rel;
  // Resolve the deepest existing ancestor of candidate.
  std::string probe = candidate;
  std::string suffix;
  while (true) {
    char resolved[PATH_MAX];
    if (realpath(probe.c_str(), resolved)) {
      std::string r(resolved);
      std::string full = suffix.empty() ? r : r + "/" + suffix;
      std::string base_s(base_real);
      if (full == base_s || full.compare(0, base_s.size() + 1, base_s + "/") == 0) {
        out = full;
        return true;
      }
      return false;
    }
    size_t slash = probe.rfind('/');
    if (slash == std::string::npos || probe == base_real) return false;
    std::string last = probe.substr(slash + 1);
    suffix = suffix.empty() ? last : last + "/" + suffix;
    probe = probe.substr(0, slash);
  }
}

// Race-free confined open: walks `rel` one component at a time from an open
// base-dir fd, with O_NOFOLLOW at every step, so user code cannot swap a
// symlink into place between a check and the use (TOCTOU). `create_dirs`
// makes intermediate directories. Returns an open fd for the final component
// (opened with `flags|O_NOFOLLOW`) or -1.
int open_confined(const std::string& base, const std::string& rel, int flags,
                  mode_t mode, bool create_dirs) {
  int cur = open(base.c_str(), O_DIRECTORY | O_RDONLY | O_CLOEXEC);
  if (cur < 0) return -1;
  size_t start = 0;
  while (true) {
    size_t slash = rel.find('/', start);
    bool last = slash == std::string::npos;
    std::string comp = rel.substr(start, last ? std::string::npos : slash - start);
    if (last) {
      int fd = openat(cur, comp.c_str(), flags | O_NOFOLLOW | O_CLOEXEC, mode);
      int saved = errno;
      close(cur);
      errno = saved;
      return fd;
    }
    if (create_dirs) {
      if (mkdirat(cur, comp.c_str(), 0777) != 0 && errno != EEXIST) {
        close(cur);
        return -1;
      }
    }
    int next = openat(cur, comp.c_str(), O_DIRECTORY | O_RDONLY | O_NOFOLLOW | O_CLOEXEC);
    int saved = errno;
    close(cur);
    errno = saved;
    if (next < 0) return -1;
    cur = next;
    start = slash + 1;
  }
}

// ---------------------------------------------------------------------------
// Workspace snapshot / diff (recursive; replaces server.rs:117-137).

struct FileSig {
  int64_t mtime_ns;
  int64_t size;
  bool operator==(const FileSig& o) const {
    return mtime_ns == o.mtime_ns && size == o.size;
  }
};

void scan_dir(const std::string& base, const std::string& rel,
              std::map<std::string, FileSig>& out) {
  std::string dir = rel.empty() ? base : base + "/" + rel;
  DIR* d = opendir(dir.c_str());
  if (!d) return;
  while (dirent* e = readdir(d)) {
    std::string name = e->d_name;
    if (name == "." || name == "..") continue;
    std::string rel_child = rel.empty() ? name : rel + "/" + name;
    std::string full = base + "/" + rel_child;
    struct stat st;
    if (lstat(full.c_str(), &st) != 0) continue;
    if (S_ISDIR(st.st_mode)) {
      scan_dir(base, rel_child, out);
    } else if (S_ISREG(st.st_mode)) {
      out[rel_child] = FileSig{
          st.st_mtim.tv_sec * 1000000000LL + st.st_mtim.tv_nsec, st.st_size};
    }
  }
  closedir(d);
}

std::vector<std::string> diff_snapshots(const std::map<std::string, FileSig>& before,
                                        const std::map<std::string, FileSig>& after) {
  std::vector<std::string> changed;
  for (const auto& [path, sig] : after) {
    auto it = before.find(path);
    if (it == before.end() || !(it->second == sig)) changed.push_back(path);
  }
  return changed;
}

// ---------------------------------------------------------------------------
// Workspace manifest: rel path -> content sha256, the executor half of the
// delta transfer protocol. Uploads hash as they stream in; the post-execute
// scan and GET /workspace-manifest rehash lazily — only entries whose
// size/mtime signature no longer matches. Protected by its own mutex
// (uploads are concurrent; /execute holds exec_mutex, which never nests
// inside this one).

struct ManifestEntry {
  std::string sha;
  FileSig sig;
};

std::map<std::string, ManifestEntry> g_ws_manifest;
std::mutex g_ws_manifest_mutex;

// Second manifest over the JAX compilation-cache dir: the executor half of
// the FLEET compile cache (control plane seeds hot entries at spawn via
// conditional PUTs and harvests new compiles at turnover via GET). Same
// entry/signature machinery as the workspace manifest, its own mutex (the
// two are never nested).
std::map<std::string, ManifestEntry> g_cc_manifest;
std::mutex g_cc_manifest_mutex;

// Hashes one workspace file through the same race-free confined open the
// transfer routes use (user code may have planted symlinks). Returns false
// when the file vanished or cannot be read; `sig_out` gets the fstat
// signature of the bytes actually hashed.
bool hash_workspace_file(const std::string& workspace, const std::string& rel,
                         std::string& hex_out, FileSig* sig_out) {
  int fd = open_confined(workspace, rel, O_RDONLY, 0, /*create_dirs=*/false);
  if (fd < 0) return false;
  struct stat st;
  if (fstat(fd, &st) != 0 || !S_ISREG(st.st_mode)) {
    close(fd);
    return false;
  }
  minisha::Sha256 hasher;
  char buf[1 << 16];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) hasher.update(buf, static_cast<size_t>(n));
  close(fd);
  if (n < 0) return false;
  hex_out = hasher.hex();
  if (sig_out) {
    *sig_out = FileSig{st.st_mtim.tv_sec * 1000000000LL + st.st_mtim.tv_nsec,
                       st.st_size};
  }
  return true;
}

// Reconciles a manifest with its base dir as it exists NOW and returns
// rel -> sha: entries whose signature still matches keep their cached sha,
// changed/new files are rehashed, gone files are dropped. Caller must NOT
// hold `mutex`. Shared by the workspace manifest and the compile-cache
// manifest.
std::map<std::string, std::string> manifest_snapshot(
    const std::string& base, std::map<std::string, ManifestEntry>& manifest,
    std::mutex& mutex) {
  std::map<std::string, FileSig> on_disk;
  scan_dir(base, "", on_disk);
  std::map<std::string, std::string> out;
  std::lock_guard<std::mutex> lock(mutex);
  for (auto it = manifest.begin(); it != manifest.end();) {
    if (on_disk.find(it->first) == on_disk.end()) {
      it = manifest.erase(it);
    } else {
      ++it;
    }
  }
  for (const auto& [rel, sig] : on_disk) {
    auto it = manifest.find(rel);
    if (it != manifest.end() && it->second.sig == sig) {
      out[rel] = it->second.sha;
      continue;
    }
    std::string hex;
    FileSig fresh;
    if (!hash_workspace_file(base, rel, hex, &fresh)) continue;
    manifest[rel] = ManifestEntry{hex, fresh};
    out[rel] = hex;
  }
  return out;
}

// After a forgivable-looking rmdir failure (EBUSY/ENOTEMPTY with the
// recursive wipe reporting success), verifies by RE-SCANNING that nothing
// but empty mount points actually remains at/below the entry. The readdir
// snapshot the wipe worked from is stale by the time rmdir fails: user
// code that escaped the runner scrub (a reparented daemon) could have
// raced a file back in, and forgiving on the stale snapshot would let it
// cross the generation boundary through a "complete" /reset. Forgivable
// residue is exactly: a mount-point directory (st_dev differs from its
// parent's) that is EMPTY, or a directory containing only such residue.
bool only_mount_residue(int dfd, const char* name) {
  struct stat parent_st;
  if (fstat(dfd, &parent_st) != 0) return false;
  int fd = openat(dfd, name, O_DIRECTORY | O_RDONLY | O_NOFOLLOW | O_CLOEXEC);
  if (fd < 0) return false;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return false;
  }
  bool is_mount = st.st_dev != parent_st.st_dev;
  DIR* d = fdopendir(fd);
  if (!d) {
    close(fd);
    return false;
  }
  bool ok = true;
  bool has_entries = false;
  while (dirent* e = readdir(d)) {
    std::string entry = e->d_name;
    if (entry == "." || entry == "..") continue;
    has_entries = true;
    if (is_mount || !only_mount_residue(dirfd(d), entry.c_str())) {
      ok = false;  // a non-empty mount point, or non-mount residue below
      break;
    }
  }
  if (!is_mount && !has_entries) {
    // An EMPTY NON-mount dir is plain removable residue, not a mount the
    // wipe is powerless against: the recursive wipe deletes empty dirs,
    // so one still standing here can only have been raced in after the
    // wipe's readdir snapshot (its NAME is attacker-chosen data). Without
    // this check the recursion forgave any empty dir — mount or not —
    // letting such names cross the generation boundary through a
    // "complete" /reset.
    ok = false;
  }
  closedir(d);
  return ok;
}

// Recursively deletes everything INSIDE dfd (the dir itself survives — it is
// the warm runner's cwd), except the subtree rooted at `preserve` (an
// absolute path; empty = preserve nothing). fd-relative with O_NOFOLLOW so
// user-planted symlinks are unlinked, never followed. `dir_path` is the
// lexical absolute path of dfd, used only for the preserve comparison.
// Returns true when every non-preserved entry was removed.
bool wipe_dirfd_children(int dfd, const std::string& dir_path,
                         const std::string& preserve) {
  DIR* d = fdopendir(dup(dfd));
  if (!d) return false;
  bool ok = true;
  while (dirent* e = readdir(d)) {
    std::string name = e->d_name;
    if (name == "." || name == "..") continue;
    std::string child_path = dir_path + "/" + name;
    if (!preserve.empty()) {
      if (child_path == preserve) {
        // The preserved subtree itself — but only if it still IS a real
        // directory. The comparison alone is lexical: user code that
        // empties the cache dir, rmdirs it, and plants a symlink (or file)
        // at the same path would get the planted node preserved through
        // /reset, redirecting the next generation's cache writes wherever
        // it points. Verify without following, unlink impostors, and
        // report the wipe incomplete so the sandbox is disposed.
        struct stat st;
        if (fstatat(dfd, name.c_str(), &st, AT_SYMLINK_NOFOLLOW) == 0 &&
            S_ISDIR(st.st_mode)) {
          continue;
        }
        if (unlinkat(dfd, name.c_str(), 0) != 0) {
          unlinkat(dfd, name.c_str(), AT_REMOVEDIR);
        }
        ok = false;
        continue;
      }
      if (preserve.rfind(child_path + "/", 0) == 0) {
        // The preserved dir lives somewhere below this child: recurse so
        // its siblings still wipe, but keep the ancestor chain intact.
        int child = openat(dfd, name.c_str(),
                           O_DIRECTORY | O_RDONLY | O_NOFOLLOW | O_CLOEXEC);
        if (child >= 0) {
          if (!wipe_dirfd_children(child, child_path, preserve)) ok = false;
          close(child);
        } else {
          // The ancestor is not an openable real dir — user code replaced
          // it (symlink/file). Reporting success would let the planted
          // node survive a "complete" wipe.
          ok = false;
        }
        continue;
      }
    }
    if (unlinkat(dfd, name.c_str(), 0) == 0) continue;
    int child = openat(dfd, name.c_str(),
                       O_DIRECTORY | O_RDONLY | O_NOFOLLOW | O_CLOEXEC);
    if (child < 0) {
      ok = false;  // neither unlinkable nor a walkable dir: left behind
      continue;
    }
    bool child_ok = wipe_dirfd_children(child, child_path, std::string());
    if (!child_ok) ok = false;
    close(child);
    if (unlinkat(dfd, name.c_str(), AT_REMOVEDIR) != 0) {
      // A fully-wiped dir can still be unremovable for two forgivable
      // reasons: it IS a mount point (EBUSY — e.g. a volume an operator
      // mounted under an extra wipe dir), or it CONTAINS one deeper down
      // (ENOTEMPTY — without this the forgiveness would stop at depth one
      // and every ancestor of a nested mount would fail the wipe). Either
      // way nothing may cross the generation boundary: child_ok is a
      // stale readdir snapshot, so only_mount_residue re-scans and
      // forgives only when empty mount points are truly all that remain.
      int err = errno;
      if (!(child_ok && (err == EBUSY || err == ENOTEMPTY) &&
            only_mount_residue(dfd, name.c_str()))) {
        ok = false;
      }
    }
  }
  closedir(d);
  return ok;
}

bool wipe_dir_children(const std::string& path,
                       const std::string& preserve = std::string()) {
  int fd = open(path.c_str(), O_DIRECTORY | O_RDONLY | O_NOFOLLOW | O_CLOEXEC);
  if (fd < 0) return false;
  bool ok = wipe_dirfd_children(fd, path, preserve);
  close(fd);
  return ok;
}

// ---------------------------------------------------------------------------
// Subprocess plumbing.

std::string read_file_capped(const std::string& path, size_t cap, bool* truncated) {
  std::string out;
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return out;
  char buf[1 << 16];
  while (out.size() < cap) {
    ssize_t n = read(fd, buf, std::min(sizeof(buf), cap - out.size()));
    if (n <= 0) break;
    out.append(buf, static_cast<size_t>(n));
  }
  // detect truncation: one more byte available?
  char extra;
  if (read(fd, &extra, 1) == 1 && truncated) *truncated = true;
  close(fd);
  return out;
}

bool write_file(const std::string& path, const std::string& data) {
  int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = write(fd, data.data() + off, data.size() - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      close(fd);
      return false;
    }
    off += static_cast<size_t>(n);
  }
  close(fd);
  return true;
}

struct ExecOutcome {
  int exit_code = -1;
  bool timed_out = false;
};

// Runs argv with stdout/stderr redirected to files, cwd=workspace, its own
// process group; kills the whole group on timeout. `rlimits` (optional)
// boxes the child with the setrlimit set before exec; `watchdog` (optional)
// learns the child pid the moment it exists, so group-level RSS/CPU/nproc
// enforcement covers the whole run.
ExecOutcome run_subprocess(const std::vector<std::string>& argv,
                           const std::string& cwd, const std::string& stdout_path,
                           const std::string& stderr_path, double timeout_s,
                           const minijson::Value* extra_env,
                           const limits::LimitSpec* rlimits = nullptr,
                           limits::Watchdog* watchdog = nullptr,
                           const std::string* cgroup_procs = nullptr) {
  ExecOutcome out;
  pid_t parent = getpid();
  pid_t pid = fork();
  if (pid < 0) return out;
  if (pid == 0) {
    setsid();
    // setsid() detaches us from the server's process group, so an external
    // SIGKILL of the server's group would orphan user code — die with the
    // server instead (checking for the fork↔prctl race). Thread-exit
    // semantics of PDEATHSIG are safe here: the forking handler thread
    // blocks in the waitpid loop below until this child is gone.
    prctl(PR_SET_PDEATHSIG, SIGKILL);
    if (getppid() != parent) _exit(127);
    // Self-attach to the per-run cgroup scope BEFORE exec (race-free:
    // every byte user code ever allocates is inside the box). Failure is
    // non-fatal — rlimits+watchdog still govern.
    if (cgroup_procs && !cgroup_procs->empty())
      cgroup::write_file(*cgroup_procs, "0");
    if (rlimits) limits::apply_child_rlimits(*rlimits);
    if (!cwd.empty()) {
      if (chdir(cwd.c_str()) != 0) _exit(127);
    }
    int so = open(stdout_path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    int se = open(stderr_path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (so >= 0) dup2(so, 1);
    if (se >= 0) dup2(se, 2);
    if (extra_env && extra_env->is_object()) {
      for (const auto& [k, v] : extra_env->as_object()) {
        // stringify non-strings for parity with the warm runner (str(v))
        std::string sv = v.is_string() ? v.as_string() : v.dump();
        setenv(k.c_str(), sv.c_str(), 1);
      }
    }
    std::vector<char*> cargv;
    for (const auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    execvp(cargv[0], cargv.data());
    _exit(127);
  }
  if (watchdog) watchdog->set_leader(pid);
  // Parent: poll for exit until deadline.
  const int tick_ms = 20;
  double waited = 0;
  int status = 0;
  while (true) {
    pid_t r = waitpid(pid, &status, WNOHANG);
    if (r == pid) {
      if (WIFEXITED(status)) out.exit_code = WEXITSTATUS(status);
      else if (WIFSIGNALED(status)) out.exit_code = 128 + WTERMSIG(status);
      return out;
    }
    if (timeout_s > 0 && waited >= timeout_s) {
      kill(-pid, SIGKILL);
      waitpid(pid, &status, 0);
      out.timed_out = true;
      out.exit_code = -1;
      return out;
    }
    usleep(tick_ms * 1000);
    waited += tick_ms / 1000.0;
  }
}

// ---------------------------------------------------------------------------
// Device-health telemetry (GET /device-stats). The repo's own bench history
// (BENCH_r03-r05) shows the worst failure mode is a wedged device op: the
// attach blocks for tens of minutes with /healthz still answering "ok",
// because nothing distinguished "busy" from "wedged". These globals are the
// raw signals a probe daemon needs to make that call: when the current
// attach (warm-up) started, when the current device op started and what its
// budget is, when the runner last produced evidence of life, and when a
// device op last SUCCEEDED. All atomics on purpose — the /device-stats
// handler must answer while exec_mutex/runner_mutex are held by exactly the
// wedged operation it exists to expose.

long long now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL;
}

std::atomic<long long> g_boot_ms{0};
// Warm-up (jax import + device attach) window: nonzero while one is running.
std::atomic<long long> g_attach_start_ms{0};
// Latency of the last SUCCESSFUL warm-up (the per-sandbox attach cost);
// -1 until one completes.
std::atomic<long long> g_attach_last_ms{-1};
// Current warm-runner device op (execute/reset round-trip): start + budget.
std::atomic<long long> g_op_start_ms{0};
std::atomic<long long> g_op_timeout_ms{0};
// Completion time of the last device op the runner answered successfully.
std::atomic<long long> g_last_op_ok_ms{0};
// Last time the runner wrote ANY bytes on its response pipe — the passive
// heartbeat. A runner pinned inside a wedged native call writes nothing, so
// this age grows exactly when the probe needs it to.
std::atomic<long long> g_runner_line_ms{0};
// Runner identity mirrors, updated only at start/kill: the stats handler
// must not touch WarmRunner fields (they are runner_mutex-protected, and
// that mutex is held for the whole duration of the op being diagnosed).
std::atomic<long long> g_runner_pid_stat{0};
std::atomic<bool> g_runner_ready_stat{false};
std::atomic<int> g_device_count_stat{0};
std::mutex g_device_info_mutex;  // guards the two strings below only
std::string g_device_backend_stat = "none";
std::string g_device_kind_stat;

// cgroup-v2 hard enforcement (cgroup.hpp): the boot-time delegation verdict,
// the long-lived scope boxing the warm runner group (bounded by the
// APP_LIMIT_* caps for the sandbox's whole life — per-request tighten-only
// overrides stay the watchdog's job), and the procs path a freshly forked
// runner self-attaches to. The verdict and its fallback reason ride
// /healthz so the control plane (and the test suite's auto-skip) can see
// which enforcement mode this sandbox actually runs in. Scope event reads
// happen only under exec_mutex (the execute/batch paths); the procs string
// is written once at boot, before any fork reads it.
cgroup::Runtime g_cgroup;
cgroup::Scope g_runner_scope;
std::string g_runner_cgroup_procs;
std::atomic<long long> g_run_scope_seq{0};

// Per-chip lease fencing: the generation token the control plane minted
// for THIS sandbox's claim on its chips, recorded at attach (POST /lease).
// Every dispatch carries its token in `x-lease-token`; a mismatch is a
// claim minted for a fenced predecessor on the same chips — rejected with
// a typed 409 BEFORE any lock is taken, so a stale dispatch cannot even
// queue behind the device plane it must never touch (the BENCH_r03-r05
// re-wedge vector). Tiny mutex, never held across I/O.
std::mutex g_lease_mutex;
std::string g_lease_token;

// Resident set size of `pid` in bytes via /proc/<pid>/statm; -1 on failure.
long long rss_bytes_of(long long pid) {
  if (pid <= 0) return -1;
  char path[64];
  snprintf(path, sizeof(path), "/proc/%lld/statm", pid);
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  long long pages_total = 0, pages_resident = 0;
  int n = fscanf(f, "%lld %lld", &pages_total, &pages_resident);
  fclose(f);
  if (n != 2) return -1;
  return pages_resident * static_cast<long long>(sysconf(_SC_PAGESIZE));
}

// ---------------------------------------------------------------------------
// Warm runner: a persistent Python process that pre-imports JAX (initializing
// the TPU) at sandbox boot and then executes scripts on demand. Protocol:
// newline-delimited JSON over the runner's fd 3 (requests) and fd 4
// (responses); user stdout/stderr go to files named in each request.

class WarmRunner {
 public:
  WarmRunner(std::string python, std::string runner_script, std::string workspace,
             double ready_timeout_s)
      : python_(std::move(python)),
        runner_script_(std::move(runner_script)),
        workspace_(std::move(workspace)),
        ready_timeout_s_(ready_timeout_s),
        interrupt_grace_s_(env_num("APP_RUNNER_INTERRUPT_GRACE_S", 20.0)) {}

  bool start() {
    int req_pipe[2];   // server writes → runner fd 3
    int resp_pipe[2];  // runner fd 4 → server reads
    if (pipe(req_pipe) != 0 || pipe(resp_pipe) != 0) return false;
    pid_t parent = getpid();
    pid_ = fork();
    if (pid_ < 0) return false;
    if (pid_ == 0) {
      setsid();
      // No PR_SET_PDEATHSIG here: it fires when the FORKING THREAD exits,
      // and runner restarts happen on short-lived per-request handler
      // threads — the fresh runner would be killed as soon as that request
      // finished. Server-death cleanup is handled by the runner itself: its
      // request-pipe read returns EOF when the server dies and it _exits
      // immediately (runner.py main loop).
      if (getppid() != parent) _exit(127);
      // Join the runner's cgroup scope BEFORE exec: from the first
      // instruction of runner.py, the kernel enforces memory.max/pids.max
      // over the whole runner group ("0" = the writing process). Failure
      // is non-fatal — the rlimits+watchdog layers still govern.
      if (!g_runner_cgroup_procs.empty())
        cgroup::write_file(g_runner_cgroup_procs, "0");
      if (chdir(workspace_.c_str()) != 0) _exit(127);
      // Shuffle pipe ends to fds 3/4 via safe high fds (the pipe fds may
      // themselves be 3/4, so a direct dup2 could clobber an end).
      int r = fcntl(req_pipe[0], F_DUPFD, 10);
      int w = fcntl(resp_pipe[1], F_DUPFD, 10);
      close(req_pipe[0]);
      close(req_pipe[1]);
      close(resp_pipe[0]);
      close(resp_pipe[1]);
      dup2(r, 3);
      dup2(w, 4);
      close(r);
      close(w);
      execlp(python_.c_str(), python_.c_str(), "-u", runner_script_.c_str(),
             (char*)nullptr);
      _exit(127);
    }
    close(req_pipe[0]);
    close(resp_pipe[1]);
    req_fd_ = req_pipe[1];
    resp_fd_ = resp_pipe[0];
    g_runner_sid = pid_;
    // Wait for the ready line (runner imports jax → can take seconds on TPU;
    // that's the point: it happens at sandbox warm-up, not at Execute time).
    std::string line;
    if (!read_line(line, ready_timeout_s_)) {
      log_msg("warm runner failed to become ready");
      stop();
      return false;
    }
    std::string device_kind;
    try {
      auto msg = minijson::parse(line);
      ready_ = msg.get_bool("ready", false);
      backend_ = msg.get_string("backend", "unknown");
      device_count_ = static_cast<int>(msg.get_number("device_count", 0));
      device_kind = msg.get_string("device_kind", "");
    } catch (...) {
      ready_ = false;
    }
    g_runner_pid_stat = pid_;
    g_runner_ready_stat = ready_;
    g_device_count_stat = device_count_;
    {
      std::lock_guard<std::mutex> dlock(g_device_info_mutex);
      g_device_backend_stat = backend_;
      g_device_kind_stat = device_kind;
    }
    log_msg("warm runner ready=%d backend=%s devices=%d", (int)ready_,
            backend_.c_str(), device_count_);
    return ready_;
  }

  bool alive() const { return pid_ > 0 && ready_; }
  pid_t pid() const { return pid_; }
  const std::string& backend() const { return backend_; }
  int device_count() const { return device_count_; }

  enum class ExecResult { kOk, kTimeout, kDied, kInterrupted };

  // Generation reset: scrub the previous sandbox's traces from the warm
  // process (stray children, workspace modules, env/cwd) while keeping the
  // device lease. False ⇒ the runner is unscrubbable (killed) and the whole
  // process must be disposed.
  bool reset(double timeout_s) {
    minijson::Value resp;
    if (execute("{\"op\":\"reset\"}", timeout_s, resp) != ExecResult::kOk)
      return false;
    if (!resp.get_bool("ok", false)) {
      kill_runner();
      return false;
    }
    return true;
  }

  // kTimeout = deadline expired (runner killed); kDied = runner crashed or
  // spoke garbage (killed); kInterrupted = deadline expired but cooperative
  // cancellation worked — the runner unwound user code via SIGINT, reported,
  // and is still alive with its device lease AND in-process state intact —
  // the caller keeps serving warm and must NOT scrub (to a session the
  // interrupt is just a failed request; pool turnover resets between
  // tenants via /reset as usual). The distinction matters doubly on a
  // leased accelerator: SIGKILLing a runner mid-device-op abandons the
  // device's server-side claim with no goodbye, which can leave the chip
  // refusing attaches until the stale claim lapses (observed on the
  // tunneled TPU: one timeout kill cost every later client a ~25-minute
  // blocked attach).
  // `allow_interrupt` gates the SIGINT grace to USER-code executes:
  // control ops (reset) must keep crisp kill-on-timeout semantics — their
  // handlers don't expect KeyboardInterrupt, and a late "interrupted"
  // verdict would misread a successful-but-slow reset as failure.
  ExecResult execute(const std::string& request_json, double timeout_s,
                     minijson::Value& response, bool allow_interrupt = false) {
    // Every runner round-trip is a device op from the probe's perspective
    // (execute, batch, reset): open the telemetry window so /device-stats
    // can report how long the CURRENT op has been running against what
    // budget, and stamp the success time when the runner actually answers.
    g_op_timeout_ms = timeout_s > 0
                          ? static_cast<long long>(timeout_s * 1000.0)
                          : 0;
    g_op_start_ms = now_ms();
    ExecResult result = execute_inner(request_json, timeout_s, response,
                                      allow_interrupt);
    if (result == ExecResult::kOk || result == ExecResult::kInterrupted)
      g_last_op_ok_ms = now_ms();
    g_op_start_ms = 0;
    return result;
  }

  ExecResult execute_inner(const std::string& request_json, double timeout_s,
                           minijson::Value& response, bool allow_interrupt) {
    std::string line = request_json + "\n";
    size_t off = 0;
    while (off < line.size()) {
      ssize_t n = write(req_fd_, line.data() + off, line.size() - off);
      if (n < 0) {
        if (errno == EINTR) continue;
        kill_runner();
        return ExecResult::kDied;
      }
      off += static_cast<size_t>(n);
    }
    std::string resp_line;
    bool timed_out = false;
    if (!read_line(resp_line, timeout_s, &timed_out)) {
      if (allow_interrupt && timed_out && interrupt_grace_s_ > 0 && pid_ > 0) {
        // Cooperative cancellation first: SIGINT surfaces in the user code
        // as KeyboardInterrupt, the runner's report-don't-die handler
        // writes a response, and the process (with its device lease)
        // survives. Python only delivers the signal between bytecodes, so
        // a runner pinned inside a long native call (an XLA compile) may
        // outlast the grace — then we fall through to the kill, as before.
        kill(-pid_, SIGINT);
        bool late_timeout = false;
        std::string late_line;
        if (read_line(late_line, interrupt_grace_s_, &late_timeout)) {
          log_msg("execute timeout: runner unwound via SIGINT (kept alive)");
          return ExecResult::kInterrupted;
        }
        log_msg("execute timeout: SIGINT grace (%.0fs) expired; killing",
                interrupt_grace_s_);
      }
      kill_runner();
      return timed_out ? ExecResult::kTimeout : ExecResult::kDied;
    }
    try {
      response = minijson::parse(resp_line);
      return ExecResult::kOk;
    } catch (...) {
      kill_runner();
      return ExecResult::kDied;
    }
  }

  void kill_runner() {
    g_runner_sid = 0;
    g_runner_pid_stat = 0;
    g_runner_ready_stat = false;
    if (pid_ > 0) {
      kill(-pid_, SIGKILL);
      waitpid(pid_, nullptr, 0);
    }
    pid_ = -1;
    ready_ = false;
    if (req_fd_ >= 0) close(req_fd_);
    if (resp_fd_ >= 0) close(resp_fd_);
    req_fd_ = resp_fd_ = -1;
    resp_buf_.clear();  // stale bytes from a dead runner must not leak forward
  }

  void stop() { kill_runner(); }

 private:
  bool read_line(std::string& line, double timeout_s, bool* timed_out = nullptr) {
    // Event-driven: poll() blocks for the full remaining budget — no
    // fixed-interval ticks on the Execute path (VERDICT r2 #6).
    struct timespec start;
    clock_gettime(CLOCK_MONOTONIC, &start);
    while (true) {
      size_t nl = resp_buf_.find('\n');
      if (nl != std::string::npos) {
        line = resp_buf_.substr(0, nl);
        resp_buf_.erase(0, nl + 1);
        return true;
      }
      int wait_ms = -1;  // no timeout: block until data or EOF
      if (timeout_s > 0) {
        struct timespec now;
        clock_gettime(CLOCK_MONOTONIC, &now);
        double elapsed = (now.tv_sec - start.tv_sec) +
                         (now.tv_nsec - start.tv_nsec) / 1e9;
        double remaining = timeout_s - elapsed;
        if (remaining <= 0) {
          if (timed_out) *timed_out = true;
          return false;
        }
        wait_ms = static_cast<int>(remaining * 1000) + 1;
      }
      struct pollfd pfd{resp_fd_, POLLIN, 0};
      int r = poll(&pfd, 1, wait_ms);
      if (r < 0 && errno != EINTR) return false;
      if (r > 0) {
        char buf[1 << 14];
        ssize_t n = read(resp_fd_, buf, sizeof(buf));
        if (n <= 0) return false;
        // Passive heartbeat: any bytes from the runner are proof of life
        // (a wedged native call writes nothing, so this age grows).
        g_runner_line_ms = now_ms();
        resp_buf_.append(buf, static_cast<size_t>(n));
      }
    }
  }

  std::string python_, runner_script_, workspace_;
  double ready_timeout_s_ = 180.0;
  double interrupt_grace_s_ = 20.0;
  pid_t pid_ = -1;
  int req_fd_ = -1, resp_fd_ = -1;
  bool ready_ = false;
  std::string backend_ = "none";
  int device_count_ = 0;
  std::string resp_buf_;
};

// ---------------------------------------------------------------------------

struct ServerState {
  std::string workspace;
  std::string runtime_packages;
  std::string python;
  std::string runner_script;
  std::string deps_script;
  std::string launch_script;
  bool warm_enabled = true;
  bool warm_eager = true;  // start warm-up at boot (pods); 0 = wait for /warmup
  bool auto_install = false;
  // Workspace-manifest protocol (delta transfers). 0 = legacy wire behavior:
  // no sha256 hashing, plain-string `files` arrays, 404 on
  // /workspace-manifest, If-None-Match ignored — exactly the pre-manifest
  // binary, which is also how the control plane's fallback path is tested.
  bool manifest_enabled = true;
  // Fleet compile cache (JAX persistent compilation cache served over
  // HTTP): the dir JAX_COMPILATION_CACHE_DIR names, exposed as
  // GET /compile-cache-manifest + hash-negotiated PUT/GET under
  // /compile-cache/. APP_COMPILE_CACHE=0 (or no cache dir) removes the
  // routes entirely — what an old binary answers too. The dir's subtree is
  // EXCLUDED from every /reset wipe: compiled kernels are exactly the
  // cross-generation state the wipe must not destroy (the historic /tmp
  // default made pod reuse silently discard them each turnover).
  std::string compile_cache_dir;
  bool compile_cache_enabled = false;
  // Extra directories whose CONTENTS are wiped on /reset (colon-separated;
  // "~/x" = HOME-relative; missing dirs are fine). Closes the cross-
  // generation channels outside workspace/runtime-packages: the sandbox's
  // private /tmp (pods; locally the backend points TMPDIR at a per-sandbox
  // dir instead — the host /tmp is shared and must not be wiped) and
  // ~/.local (pip --user installs land on sys.path).
  std::vector<std::string> extra_wipe_dirs;
  int num_hosts = 1;  // >1 → this sandbox is one host of a multi-host slice
  double default_timeout = 60.0;
  size_t max_output = 10 * 1024 * 1024;
  // Resource-governance caps-and-defaults (APP_LIMIT_*; see limits.hpp) and
  // the watchdog's sampling cadence.
  limits::LimitSpec limit_caps;
  double limit_poll_interval = 0.1;
  // Strict lease-token mode (APP_LEASE_REQUIRE_TOKEN=1): once a lease is
  // recorded, a dispatch WITHOUT an x-lease-token is refused with a typed
  // 409 — for fleets whose control planes all stamp tokens (PR 13), where
  // a tokenless dispatch can only be a stale/foreign claim. Default off:
  // tokenless compatibility for old control planes and manual curl.
  bool lease_require_token = false;
  WarmRunner* runner = nullptr;
  std::mutex exec_mutex;
  std::mutex runner_mutex;
};

ServerState g_state;

// Warm-up state machine. The server announces its port and serves HTTP from
// the moment it boots; the warm runner's jax import / TPU init (seconds to
// minutes) runs on a background thread. Round 1 serialized these — readiness
// waited on TPU init, so any init slower than the control plane's ready
// timeout failed every spawn (the r01 bench killer). Now "reachable" and
// "TPU-hot" are separate facts: /healthz reports warm_state, /readyz gates
// k8s readiness on it, POST /warmup lets the control plane decide WHEN init
// runs (it holds the per-chip lease — see backends/local.py).
enum WarmState { kWarmOff = 0, kWarmPending = 1, kWarmReady = 2, kWarmFailed = 3 };
std::atomic<int> g_warm_state{kWarmOff};
std::atomic<bool> g_ever_ready{false};
std::mutex g_warm_transition_mutex;
// Signaled on every warm-state transition so execute-path waiters block on a
// condvar instead of spinning (VERDICT r2 #6).
std::condition_variable g_warm_cv;

const char* warm_state_name(int s) {
  switch (s) {
    case kWarmPending: return "pending";
    case kWarmReady: return "ready";
    case kWarmFailed: return "failed";
    default: return "off";
  }
}

// Kick off (or retry) warm-up on a background thread. Idempotent: no-op when
// already pending/ready. Failed → pending retries (used for the
// off-critical-path runner restart after a timeout kill).
void start_warm_async() {
  if (!g_state.warm_enabled || !g_state.runner) return;
  {
    std::lock_guard<std::mutex> l(g_warm_transition_mutex);
    int s = g_warm_state.load();
    if (s == kWarmPending || s == kWarmReady) return;
    if (s == kWarmFailed && g_state.num_hosts > 1) return;  // see below
    g_warm_state = kWarmPending;
    g_attach_start_ms = now_ms();  // the attach window /device-stats reports
  }
  std::thread([] {
    bool ok;
    {
      std::lock_guard<std::mutex> l(g_state.runner_mutex);
      ok = g_state.runner->start();
    }
    if (ok) g_ever_ready = true;
    long long attach_start = g_attach_start_ms.load();
    if (ok && attach_start > 0) g_attach_last_ms = now_ms() - attach_start;
    g_attach_start_ms = 0;
    {
      std::lock_guard<std::mutex> l(g_warm_transition_mutex);
      g_warm_state = ok ? kWarmReady : kWarmFailed;
    }
    g_warm_cv.notify_all();
    if (!ok) {
      // On a multi-host slice the runner IS the jax.distributed membership;
      // a lone restart could never rendezvous (its peers' runners are still
      // in the old cluster), so failure is terminal and the control plane
      // must dispose the whole slice group.
      log_msg("warm-up failed%s", g_state.num_hosts > 1
                                      ? " on a multi-host slice (terminal)"
                                      : "");
    }
  }).detach();
}

const std::string* prefix_base(const std::string& prefix) {
  if (prefix == "workspace") return &g_state.workspace;
  if (prefix == "runtime-packages") return &g_state.runtime_packages;
  if (prefix == "compile-cache" && g_state.compile_cache_enabled)
    return &g_state.compile_cache_dir;
  return nullptr;
}

// The manifest (map + mutex) negotiating transfers for a prefix, or
// nullptrs for unmanifested prefixes (runtime-packages; everything when
// the protocol is off).
void prefix_manifest(const std::string& prefix,
                     std::map<std::string, ManifestEntry>*& map_out,
                     std::mutex*& mutex_out) {
  map_out = nullptr;
  mutex_out = nullptr;
  if (prefix == "workspace" && g_state.manifest_enabled) {
    map_out = &g_ws_manifest;
    mutex_out = &g_ws_manifest_mutex;
  } else if (prefix == "compile-cache" && g_state.compile_cache_enabled) {
    map_out = &g_cc_manifest;
    mutex_out = &g_cc_manifest_mutex;
  }
}

// Splits "/workspace/a/b" → ("workspace", "a/b"). Tolerates the reference
// control plane's double-prefix URLs ("/workspace//workspace/x" — SURVEY.md
// §0.4) by stripping a repeated leading prefix segment.
bool split_target(const std::string& target, std::string& prefix, std::string& rel) {
  std::string t = target;
  while (!t.empty() && t[0] == '/') t.erase(0, 1);
  size_t slash = t.find('/');
  if (slash == std::string::npos) return false;
  prefix = t.substr(0, slash);
  rel = sanitize_rel_path(t.substr(slash + 1));
  if (rel.empty()) return false;
  // strip duplicated prefix ("workspace/workspace/x" from legacy clients)
  std::string dup = prefix + "/";
  if (rel.compare(0, dup.size(), dup) == 0) rel = rel.substr(dup.size());
  return !rel.empty();
}

void handle_upload(const minihttp::Request& req, minihttp::Conn& conn) {
  std::string prefix, rel;
  if (!split_target(req.target, prefix, rel)) {
    conn.drain_body();
    conn.send_response(400, "application/json", "{\"error\":\"bad path\"}");
    return;
  }
  const std::string* base = prefix_base(prefix);
  if (!base) {
    conn.drain_body();
    conn.send_response(404, "application/json", "{\"error\":\"unknown prefix\"}");
    return;
  }
  std::map<std::string, ManifestEntry>* mani = nullptr;
  std::mutex* mani_mutex = nullptr;
  prefix_manifest(prefix, mani, mani_mutex);
  bool manifested = mani != nullptr;
  // Conditional upload: `If-None-Match: <sha256 of the body being sent>`.
  // When the manifest says the file at `rel` already holds exactly that
  // content (and the disk signature still matches — user code may have
  // touched it since), the body is drained and skipped with a 304: no disk
  // write, no rehash. On mismatch the PUT proceeds as a normal upload — the
  // header is a claim about the body, so writing it is always correct.
  std::string cond = req.header("if-none-match");
  if (!cond.empty() && cond.front() == '"' && cond.back() == '"' && cond.size() >= 2)
    cond = cond.substr(1, cond.size() - 2);
  if (manifested && !cond.empty()) {
    bool matches = false;
    FileSig cached{0, 0};
    {
      std::lock_guard<std::mutex> lock(*mani_mutex);
      auto it = mani->find(rel);
      if (it != mani->end() && it->second.sha == cond) {
        matches = true;
        cached = it->second.sig;
      }
    }
    if (matches) {
      struct stat st;
      int fd = open_confined(*base, rel, O_RDONLY, 0, /*create_dirs=*/false);
      bool fresh = fd >= 0 && fstat(fd, &st) == 0 && S_ISREG(st.st_mode) &&
                   FileSig{st.st_mtim.tv_sec * 1000000000LL + st.st_mtim.tv_nsec,
                           st.st_size} == cached;
      if (fd >= 0) close(fd);
      if (fresh) {
        conn.drain_body();
        conn.send_response(304, "application/json", "");
        return;
      }
    }
  }
  int fd = open_confined(*base, rel, O_WRONLY | O_CREAT | O_TRUNC, 0644,
                         /*create_dirs=*/true);
  if (fd < 0) {
    conn.drain_body();
    int status = errno == ELOOP || errno == ENOTDIR ? 403 : 500;
    conn.send_response(status, "application/json",
                       "{\"error\":\"open failed (confined)\"}");
    return;
  }
  // Workspace disk quota guards the streaming path too: without it a client
  // (or a compromised control plane) could fill the sandbox disk through
  // PUTs that never run any code. Usage is measured once at upload start
  // (after O_TRUNC zeroed any file being overwritten) and this body's bytes
  // count against the remainder. With the manifest on, usage comes from the
  // cached entry sizes (O(entries), no IO) — a full recursive walk per PUT
  // would make an N-file sync O(N^2) stats; without it, the walk.
  long long disk_cap =
      prefix == "workspace" ? g_state.limit_caps.disk_bytes : 0;
  long long usage_before = 0;
  if (disk_cap > 0) {
    if (manifested) {
      // Exclude the entry for the path being overwritten: O_TRUNC above
      // already freed those bytes, so counting the stale size would 413
      // legitimate re-uploads of changed files (the delta-sync's normal
      // path) on any workspace near half its quota.
      std::lock_guard<std::mutex> lock(*mani_mutex);
      for (const auto& [entry_rel, entry] : *mani)
        if (entry_rel != rel) usage_before += entry.sig.size;
    } else {
      usage_before = limits::dir_usage_bytes(*base);
    }
  }
  // Stream-hash while writing: the manifest learns the sha at upload time,
  // so the post-execute scan never rehashes bytes the PUT already saw.
  minisha::Sha256 hasher;
  size_t total = 0;
  try {
    std::string chunk;
    while (true) {
      chunk.clear();
      if (conn.read_body_some(chunk, 1 << 20) == 0) break;
      if (disk_cap > 0 &&
          usage_before + static_cast<long long>(total + chunk.size()) >
              disk_cap) {
        // Over quota: give the quota back (truncate what we wrote), drop
        // the stale manifest entry, and answer with the typed violation.
        ftruncate(fd, 0);
        close(fd);
        if (manifested) {
          std::lock_guard<std::mutex> lock(*mani_mutex);
          mani->erase(rel);
        }
        conn.drain_body();
        conn.send_response(
            413, "application/json",
            "{\"error\":\"workspace disk quota exceeded\","
            "\"violation\":\"disk_quota\"}");
        return;
      }
      if (manifested) hasher.update(chunk.data(), chunk.size());
      size_t off = 0;
      while (off < chunk.size()) {
        ssize_t n = write(fd, chunk.data() + off, chunk.size() - off);
        if (n < 0) {
          if (errno == EINTR) continue;
          close(fd);
          conn.send_response(500, "application/json",
                             "{\"error\":\"write failed\"}");
          return;
        }
        off += static_cast<size_t>(n);
      }
      total += chunk.size();
    }
  } catch (...) {
    // Client aborted mid-body (the control plane cancels sibling uploads
    // when one fails): the connection is already doomed, but a long-lived
    // warm sandbox must not leak one fd per aborted PUT until EMFILE.
    close(fd);
    throw;
  }
  struct stat st;
  bool have_sig = fstat(fd, &st) == 0;
  close(fd);
  minijson::Object resp;
  resp["path"] = minijson::Value("/" + prefix + "/" + rel);
  resp["size"] = minijson::Value(static_cast<int64_t>(total));
  if (manifested) {
    std::string sha = hasher.hex();
    if (have_sig) {
      std::lock_guard<std::mutex> lock(*mani_mutex);
      (*mani)[rel] = ManifestEntry{
          sha,
          FileSig{st.st_mtim.tv_sec * 1000000000LL + st.st_mtim.tv_nsec,
                  st.st_size}};
    }
    resp["sha256"] = minijson::Value(sha);
  }
  conn.send_response(200, "application/json", minijson::Value(resp).dump());
}

// GET /workspace-manifest — the resync surface: the full rel -> sha256 map
// of the workspace as it exists now (lazily rehashed). 404 when the
// manifest protocol is disabled, which is what an old binary answers too —
// the control plane treats both identically (full-transfer fallback).
void handle_manifest(const minihttp::Request&, minihttp::Conn& conn) {
  if (!g_state.manifest_enabled) {
    conn.send_response(404, "application/json", "{\"error\":\"no route\"}");
    return;
  }
  minijson::Object files;
  for (const auto& [rel, sha] :
       manifest_snapshot(g_state.workspace, g_ws_manifest, g_ws_manifest_mutex)) {
    files[rel] = minijson::Value(sha);
  }
  minijson::Object resp;
  resp["files"] = minijson::Value(files);
  conn.send_response(200, "application/json", minijson::Value(resp).dump());
}

// jax keeps 8-byte "-atime" sidecars beside each cache entry (its own
// local LRU bookkeeping, rewritten on every cache READ). They are per-host
// state with no fleet meaning and would churn the manifest on every hit —
// keep them out of the protocol entirely.
bool cc_entry_ignored(const std::string& rel) {
  static const std::string kSuffix = "-atime";
  return rel.size() >= kSuffix.size() &&
         rel.compare(rel.size() - kSuffix.size(), kSuffix.size(), kSuffix) == 0;
}

// GET /compile-cache-manifest — the fleet compile cache's negotiation
// surface: rel -> sha256 of every entry in the JAX compilation-cache dir
// (lazily rehashed, exactly like the workspace manifest). The control
// plane seeds against it at spawn (only missing entries cross the wire)
// and harvests against it at turnover (only never-seen entries come back).
// 404 when the compile cache is off — what an old binary answers too.
void handle_cc_manifest(const minihttp::Request&, minihttp::Conn& conn) {
  if (!g_state.compile_cache_enabled) {
    conn.send_response(404, "application/json", "{\"error\":\"no route\"}");
    return;
  }
  minijson::Object files;
  for (const auto& [rel, sha] : manifest_snapshot(
           g_state.compile_cache_dir, g_cc_manifest, g_cc_manifest_mutex)) {
    if (cc_entry_ignored(rel)) continue;
    files[rel] = minijson::Value(sha);
  }
  minijson::Object resp;
  resp["files"] = minijson::Value(files);
  conn.send_response(200, "application/json", minijson::Value(resp).dump());
}

void handle_download(const minihttp::Request& req, minihttp::Conn& conn) {
  std::string prefix, rel;
  if (!split_target(req.target, prefix, rel)) {
    conn.send_response(400, "application/json", "{\"error\":\"bad path\"}");
    return;
  }
  const std::string* base = prefix_base(prefix);
  if (!base) {
    conn.send_response(404, "application/json", "{\"error\":\"unknown prefix\"}");
    return;
  }
  int fd = open_confined(*base, rel, O_RDONLY, 0, /*create_dirs=*/false);
  if (fd < 0) {
    // Linux reports a refused symlink component as ELOOP (final) or ENOTDIR
    // (O_DIRECTORY|O_NOFOLLOW on an intermediate symlink).
    int status = errno == ELOOP || errno == ENOTDIR ? 403 : 404;
    conn.send_response(status, "application/json", "{\"error\":\"not found\"}");
    return;
  }
  if (!conn.send_file_fd(fd)) {  // closes fd
    conn.send_response(404, "application/json", "{\"error\":\"not a file\"}");
  }
}

void maybe_install_deps(const std::string& script_path) {
  if (!g_state.auto_install) return;
  std::string out_path = "/tmp/deps-out-" + std::to_string(getpid());
  ExecOutcome guess = run_subprocess(
      {g_state.python, g_state.deps_script, script_path, g_state.runtime_packages},
      "", out_path, "/dev/null", 30.0, nullptr);
  if (guess.exit_code != 0) return;
  std::string missing = read_file_capped(out_path, 1 << 16, nullptr);
  unlink(out_path.c_str());
  std::vector<std::string> pkgs;
  std::string cur;
  for (char c : missing + "\n") {
    if (c == '\n') {
      if (!cur.empty()) pkgs.push_back(cur);
      cur.clear();
    } else if (c != '\r') {
      cur += c;
    }
  }
  if (pkgs.empty()) return;
  std::vector<std::string> argv = {g_state.python, "-m", "pip", "install",
                                   "--no-cache-dir"};
  for (const auto& p : pkgs) argv.push_back(p);
  log_msg("auto-installing %zu missing deps", pkgs.size());
  run_subprocess(argv, "", "/dev/null", "/dev/null", 240.0, nullptr);
}

// Follows one capture file during a streaming execute, emitting
// {"stream":...,"data":...} NDJSON events for bytes appended since the last
// pump. Capped at `limit` bytes per stream (the final result object carries
// the truncation marker); the file may not exist yet on the first pumps.
class StreamTail {
 public:
  StreamTail(std::string path, std::string name, size_t limit)
      : path_(std::move(path)), name_(std::move(name)), limit_(limit) {}

  void pump(minihttp::Conn& conn) {
    if (sent_ >= limit_) return;
    int fd = ::open(path_.c_str(), O_RDONLY);
    if (fd < 0) return;  // not created yet
    if (lseek(fd, static_cast<off_t>(offset_), SEEK_SET) < 0) {
      ::close(fd);
      return;
    }
    char buf[1 << 16];
    std::string fresh;
    ssize_t n;
    while ((n = ::read(fd, buf, sizeof(buf))) > 0) {
      fresh.append(buf, static_cast<size_t>(n));
      if (offset_ + fresh.size() - sent_ > (1 << 20)) break;  // bounded batch
    }
    ::close(fd);
    if (fresh.empty()) return;
    // Never split a multi-byte UTF-8 character across two JSON events: the
    // client decodes each event's string independently, and a split
    // codepoint becomes U+FFFD on both sides. Hold incomplete trailing
    // bytes for the next pump (the final result body reads the raw file,
    // so nothing is ever lost to the hold-back).
    size_t emit_len = utf8_complete_prefix(fresh);
    if (emit_len == 0) return;
    fresh.resize(emit_len);
    offset_ += fresh.size();
    if (sent_ + fresh.size() > limit_) {
      fresh.resize(limit_ - sent_);
      fresh.resize(utf8_complete_prefix(fresh));  // cap edge, same rule
    }
    sent_ += fresh.size();
    if (fresh.empty()) return;
    minijson::Object event;
    event["stream"] = minijson::Value(name_);
    event["data"] = minijson::Value(fresh);
    conn.send_chunk(minijson::Value(event).dump() + "\n");
  }

  // Length of the longest prefix ending on a UTF-8 character boundary.
  // Invalid sequences (binary output) are passed through whole rather than
  // held forever: only a genuine incomplete multi-byte tail is trimmed.
  static size_t utf8_complete_prefix(const std::string& s) {
    if (s.empty()) return 0;
    size_t i = s.size();
    size_t back = 0;
    while (i > 0 && back < 4) {
      unsigned char c = static_cast<unsigned char>(s[i - 1]);
      if (c < 0x80) return s.size();  // ASCII tail: everything complete
      if ((c & 0xC0) == 0xC0) {
        // Lead byte at i-1 with `back` continuation bytes after it.
        size_t need = (c & 0xE0) == 0xC0   ? 1
                      : (c & 0xF0) == 0xE0 ? 2
                      : (c & 0xF8) == 0xF0 ? 3
                                           : 0;  // invalid lead: pass through
        if (need == 0 || need == back) return s.size();
        return need > back ? i - 1 : s.size();
      }
      --i;  // continuation byte, keep scanning back
      ++back;
    }
    return s.size();  // >=4 trailing continuation bytes: invalid, pass through
  }

 private:
  std::string path_;
  std::string name_;
  size_t limit_;
  size_t offset_ = 0;  // bytes consumed from the file
  size_t sent_ = 0;    // bytes emitted to the client (<= limit_)
};

// Outcome of one user-code run (warm runner or cold subprocess).
struct RunOutcome {
  int exit_code = -1;
  bool timed_out = false;
  bool runner_died = false;
  bool ran_warm = false;
  bool restarted = false;  // warm runner kill/crash -> background rewarm
  bool multi_host_refused = false;
  // Typed resource-limit violation ("" = none): which limit killed the run
  // (watchdog/rlimit) or fired in-process (the runner's soft guards).
  std::string violation;
  // Persistent-compilation-cache traffic observed by the warm runner's
  // jax.monitoring listener during this run (-1 = not reported: cold
  // subprocess, old runner, or jax without the monitoring surface).
  long long cache_hits = -1;
  long long cache_misses = -1;
  // Device-memory accounting block the warm runner sampled around the run
  // (live/peak device-buffer bytes + runner RSS) — present only when the
  // request asked for it AND the runner could measure (warm path; the cold
  // subprocess has no instrumented interpreter to sample).
  minijson::Value device_memory;
};

// The execution core shared by /execute and /execute/stream: run the script
// through the warm runner when available, else a cold subprocess; stdout/
// stderr land in the given capture files (which is what makes streaming
// possible — a tailer can follow them while this blocks).
// The in-process guards the warm runner applies itself (runner.py): a JSON
// object for the runner request's `limits` key. Group-level bounds (nproc,
// disk, memory-as-RSS) are the watchdog's job and stay out.
minijson::Value runner_limits_json(const limits::LimitSpec& lim) {
  minijson::Object o;
  if (lim.memory_bytes > 0)
    o["memory_bytes"] = minijson::Value(static_cast<int64_t>(lim.memory_bytes));
  if (lim.cpu_seconds > 0) o["cpu_seconds"] = minijson::Value(lim.cpu_seconds);
  if (lim.nofile > 0)
    o["nofile"] = minijson::Value(static_cast<int64_t>(lim.nofile));
  if (lim.fsize_bytes > 0)
    o["fsize_bytes"] = minijson::Value(static_cast<int64_t>(lim.fsize_bytes));
  return minijson::Value(o);
}

// The 32-hex trace id inside a W3C traceparent ("00-<trace>-<span>-<fl>"),
// or "" — forwarded to the warm runner so its own log lines (and a batch
// job's) are attributable to the originating request.
std::string trace_id_of(const std::string& traceparent) {
  size_t a = traceparent.find('-');
  if (a == std::string::npos) return "";
  size_t b = traceparent.find('-', a + 1);
  if (b == std::string::npos || b - a != 33) return "";
  return traceparent.substr(a + 1, 32);
}

RunOutcome run_user_code(const std::string& script_path,
                         const std::string& stdout_path,
                         const std::string& stderr_path, double timeout_s,
                         const minijson::Value& extra_env,
                         const limits::LimitSpec& lim,
                         const std::string& trace_id = "",
                         bool want_device_memory = false) {
  RunOutcome out;
  bool restart_runner = false;

  if (g_state.warm_enabled && g_state.runner) {
    // Initial warm-up may still be in flight (the control plane normally
    // gates on /healthz warm before admitting a sandbox, but direct clients
    // and eager-mode pods can race it). Racing a cold subprocess against the
    // runner's TPU init would make both fight over the chip — wait it out.
    // Bounded: the warm thread resolves within the runner's ready timeout.
    // A RESTART in flight (g_ever_ready) is different: the previous request
    // timed out, and the next one must not pay TPU re-init on its critical
    // path — it falls through to the cold subprocess immediately.
    {
      std::unique_lock<std::mutex> wl(g_warm_transition_mutex);
      g_warm_cv.wait(wl, [] {
        return g_warm_state.load() != kWarmPending || g_ever_ready.load();
      });
    }
    if (g_warm_state.load() == kWarmReady) {
      std::lock_guard<std::mutex> rlock(g_state.runner_mutex);
      if (g_state.runner->alive()) {
        minijson::Object reqo;
        reqo["source_path"] = minijson::Value(script_path);
        reqo["stdout_path"] = minijson::Value(stdout_path);
        reqo["stderr_path"] = minijson::Value(stderr_path);
        if (!trace_id.empty()) reqo["trace_id"] = minijson::Value(trace_id);
        if (want_device_memory) reqo["device_memory"] = minijson::Value(true);
        if (extra_env.is_object()) reqo["env"] = extra_env;
        if (lim.any()) reqo["limits"] = runner_limits_json(lim);
        minijson::Value resp;
        // Layered enforcement: the runner's in-process soft guards report
        // cleanly and keep the process (and its device lease) alive; the
        // watchdog is the backstop that kills the whole runner group when
        // user code dodges them (native allocs, children, masked signals).
        limits::Watchdog wd(lim, g_state.runner->pid(), g_state.workspace,
                            {stdout_path, stderr_path},
                            g_state.limit_poll_interval);
        wd.start();
        // Bracket the run with the runner scope's kernel event counters:
        // a memory.max OOM kill / pids.max fork refusal DURING this run
        // reclassifies a generic runner death below.
        g_runner_scope.refresh_baseline();
        WarmRunner::ExecResult r = g_state.runner->execute(
            minijson::Value(reqo).dump(), timeout_s > 0 ? timeout_s + 0.5 : 0,
            resp, /*allow_interrupt=*/true);
        wd.stop();
        out.ran_warm = true;
        switch (r) {
          case WarmRunner::ExecResult::kOk:
            out.exit_code = static_cast<int>(resp.get_number("exit_code", -1));
            out.violation = resp.get_string("violation", "");
            out.cache_hits =
                static_cast<long long>(resp.get_number("cache_hits", -1));
            out.cache_misses =
                static_cast<long long>(resp.get_number("cache_misses", -1));
            out.device_memory = resp.get("device_memory");
            break;
          case WarmRunner::ExecResult::kTimeout:
            out.timed_out = true;
            restart_runner = true;
            break;
          case WarmRunner::ExecResult::kInterrupted:
            // Timed out, but cooperative cancellation unwound the user code
            // and the runner survived with its device lease AND state. No
            // scrub here: to a session the interrupt is just an exception
            // (its in-process state legitimately lives on, like any other
            // failed request), and pool turnover already resets between
            // tenants via /reset — an immediate scrub would silently break
            // the session contract while runner_restarted=false claims
            // state survived.
            out.timed_out = true;
            break;
          case WarmRunner::ExecResult::kDied:
            out.runner_died = true;
            restart_runner = true;
            break;
        }
        // A watchdog kill reaches the server as kDied/kTimeout (the runner
        // group is gone mid-request); the recorded kind reclassifies that
        // generic death as the typed violation it actually was. The
        // cgroup scope's event deltas do the same for KERNEL kills the
        // watchdog never saw coming (allocation bursts faster than one
        // sampling tick) — watchdog verdicts win when both fired.
        std::string wd_kind = wd.violation();
        if (!wd_kind.empty()) out.violation = wd_kind;
        if (out.violation.empty()) {
          const char* cg_kind = g_runner_scope.violation();
          if (cg_kind) out.violation = cg_kind;
        }
      } else {
        // Runner found already dead at request time (e.g. OOM-killed
        // between requests): without flagging a restart here, the sandbox
        // would serve every subsequent request cold forever (sessions
        // never hit /reset, where dead-runner recovery otherwise lives)
        // and runner_restarted=false would hide the in-process state loss
        // from the control plane's session tracking. The request itself
        // still runs via the cold path below — no stderr pollution.
        restart_runner = true;
      }
    }
    if (restart_runner) {
      // Off the critical path: restart in the background; this response (and
      // any request landing before the restart finishes) is served cold.
      g_warm_state = kWarmFailed;
      start_warm_async();
    }
  }
  out.restarted = restart_runner;

  if (!out.ran_warm) {
    if (g_state.num_hosts > 1) {
      // A multi-host slice only exists through the warm runner's
      // jax.distributed mesh; a cold subprocess here would run user code
      // with a silently missing mesh — fail loudly instead.
      out.multi_host_refused = true;
      return out;
    }
    // launch.py wraps runpy with the same shell-syntax fallback the warm
    // runner applies (mixed Python/shell snippets — the xonsh role).
    // The cold child gets the real setrlimit set (it is wholly the user's)
    // plus the same watchdog backstop; the leader pid binds post-fork.
    limits::Watchdog wd(lim, 0, g_state.workspace, {stdout_path, stderr_path},
                        g_state.limit_poll_interval);
    wd.start();
    // Per-run cgroup scope (hard kernel backstop; throwaway). The memory
    // bound carries headroom above the watchdog's own slacked threshold —
    // the budget means "beyond baseline" and a cgroup counts from zero,
    // so the box must absorb the cold interpreter's startup RSS too; the
    // pids bound leaves room for the launch wrapper and interpreter
    // threads. Normal breaches still get the watchdog's clean typed kill;
    // the cgroup catches what outruns its sampling tick.
    cgroup::Scope run_scope;
    std::string run_procs;
    if (g_cgroup.enabled && (lim.memory_bytes > 0 || lim.nproc > 0)) {
      char scope_name[64];
      snprintf(scope_name, sizeof(scope_name), "run-%lld",
               static_cast<long long>(g_run_scope_seq.fetch_add(1) + 1));
      long long mem_headroom = lim.memory_bytes > (256LL << 20)
                                   ? lim.memory_bytes
                                   : (256LL << 20);
      run_scope = cgroup::Scope::create(
          g_cgroup, scope_name,
          lim.memory_bytes > 0 ? lim.memory_bytes + mem_headroom : 0,
          lim.nproc > 0 ? lim.nproc + 32 : 0);
      if (run_scope.active()) run_procs = run_scope.procs_path();
    }
    ExecOutcome cold = run_subprocess(
        {g_state.python, g_state.launch_script, script_path}, g_state.workspace,
        stdout_path, stderr_path, timeout_s, &extra_env, &lim, &wd,
        run_procs.empty() ? nullptr : &run_procs);
    wd.stop();
    out.exit_code = cold.exit_code;
    out.timed_out = cold.timed_out;
    out.violation = wd.violation();
    if (out.violation.empty() && lim.cpu_seconds > 0 &&
        cold.exit_code == 128 + SIGXCPU) {
      // RLIMIT_CPU fired in the child (no handler there): the kernel's
      // SIGXCPU kill IS the cpu_time violation.
      out.violation = limits::kCpuTime;
    }
    if (out.violation.empty()) {
      // Kernel-side enforcement evidence: an OOM kill at memory.max or a
      // fork refused at pids.max is the typed violation the generic exit
      // code hid.
      const char* cg_kind = run_scope.violation();
      if (cg_kind && (cold.exit_code != 0 || strcmp(cg_kind, limits::kOom) == 0))
        out.violation = cg_kind;
    }
    if (!run_scope.destroy()) {
      log_msg("cgroup scope %s would not die; leaking one empty dir",
              run_scope.dir().c_str());
    }
  }
  return out;
}

// POST /lease — record this sandbox's lease generation token. FIRST-WRITE-
// WINS for the process's lifetime: the control plane pushes exactly once,
// right after spawn and BEFORE the sandbox serves anything — so the only
// party that can ever land the first write is the control plane, and a
// later rotation attempt (tenant code curling localhost from inside the
// sandbox — this route is as reachable as /reset, but a forged rotation
// here would make the control plane's REAL token read stale and convert
// every request into an unbilled dispose-and-respawn) is refused with a
// 409. Re-posting the SAME token is an idempotent 200 (push retries).
void handle_lease(const minihttp::Request&, minihttp::Conn& conn) {
  std::string body = conn.read_body();
  std::string token;
  try {
    minijson::Value parsed = minijson::parse(body);
    token = parsed.get_string("token");
  } catch (const std::exception&) {
    conn.send_response(400, "application/json", "{\"error\":\"bad json\"}");
    return;
  }
  if (token.empty()) {
    conn.send_response(400, "application/json",
                       "{\"error\":\"token required\"}");
    return;
  }
  std::string conflict;
  {
    // Decide under the lock, respond outside it (never held across I/O).
    std::lock_guard<std::mutex> lock(g_lease_mutex);
    if (!g_lease_token.empty() && g_lease_token != token) {
      conflict = g_lease_token;
    } else {
      g_lease_token = token;
    }
  }
  if (!conflict.empty()) {
    log_msg("lease rotation refused: held=%s offered=%s", conflict.c_str(),
            token.c_str());
    // Held token log-only, like the dispatch refusals: a tenant POSTing a
    // bogus rotation from inside the sandbox must not be handed the real
    // credential in the refusal body.
    minijson::Object err;
    err["error"] = minijson::Value(std::string("lease_already_recorded"));
    conn.send_response(409, "application/json", minijson::Value(err).dump());
    return;
  }
  log_msg("lease token recorded: %s", token.c_str());
  minijson::Object resp;
  resp["ok"] = minijson::Value(true);
  resp["token"] = minijson::Value(token);
  conn.send_response(200, "application/json", minijson::Value(resp).dump());
}

// The fencing check: a request presenting a lease token that does not
// match the one this server holds is a claim minted for a fenced
// predecessor — refuse with the typed 409 and touch NOTHING (no mutex, no
// body parse, no device plane). Requests without the header (old control
// planes, manual curl) and servers without a recorded token (old control
// plane never POSTed /lease) pass through: enforcement is opt-in per hop,
// the control-plane revocation check is the backstop.
bool reject_stale_lease(const minihttp::Request& req, minihttp::Conn& conn) {
  std::string offered = req.header("x-lease-token");
  std::string held;
  {
    std::lock_guard<std::mutex> lock(g_lease_mutex);
    held = g_lease_token;
  }
  if (offered.empty()) {
    // Strict mode (APP_LEASE_REQUIRE_TOKEN=1): once a lease is recorded,
    // a tokenless dispatch is refused with its own typed 409 — on a
    // fully-rolled fleet every legitimate dispatch carries the token, so
    // "no token" can only be an old/foreign control plane or tenant code
    // curling the data plane from inside the sandbox. BEFORE any lease is
    // recorded, tokenless passes even in strict mode (boot-time probes,
    // the control plane's own pre-lease traffic).
    if (!g_state.lease_require_token || held.empty()) return false;
    log_msg("tokenless dispatch refused (strict lease mode; held=%s)",
            held.c_str());
    conn.drain_body();
    // The held token stays OUT of the body (log-only): this refusal is
    // exactly what tenant code curling the data plane from inside the
    // sandbox sees, and echoing the valid token would hand it the replay
    // credential the strict gate exists to demand.
    minijson::Object err;
    err["error"] = minijson::Value(std::string("lease_token_required"));
    conn.send_response(409, "application/json", minijson::Value(err).dump());
    return true;
  }
  if (held.empty() || offered == held) return false;
  log_msg("stale lease claim refused: offered=%s held=%s", offered.c_str(),
          held.c_str());
  conn.drain_body();
  minijson::Object err;
  err["error"] = minijson::Value(std::string("stale_lease"));
  // `offered` is the caller's own (stale) token — safe to echo for the
  // control plane's diagnostics. The HELD token is log-only: echoing the
  // successor's valid credential to whoever presented a stale one would
  // let any sandbox-internal caller harvest it with a junk claim.
  err["offered"] = minijson::Value(offered);
  conn.send_response(409, "application/json", minijson::Value(err).dump());
  return true;
}

// The canonical result hash for declared-pure runs: sha256 over stdout,
// stderr, the decimal exit code, and the SORTED changed-file content
// hashes, each part NUL-terminated. The control plane re-derives this from
// the very wire fields it received (result_content_sha in
// services/result_memo.py) and records nothing on a mismatch — the memo's
// end-to-end integrity check.
std::string pure_result_sha256(const std::string& out_s,
                               const std::string& err_s, int exit_code,
                               std::vector<std::string> file_shas) {
  std::sort(file_shas.begin(), file_shas.end());
  minisha::Sha256 h;
  auto part = [&h](const std::string& s) {
    h.update(s.data(), s.size());
    h.update("\0", 1);
  };
  part(out_s);
  part(err_s);
  part(std::to_string(exit_code));
  for (const auto& sha : file_shas) part(sha);
  return h.hex();
}

void handle_execute_impl(const minihttp::Request& req, minihttp::Conn& conn,
                         bool streaming) {
  // Lease fencing FIRST: a stale claim must be refused before the body is
  // even read, and above all before exec_mutex — a wedged op may be
  // holding that lock for minutes, and a stale dispatch queueing behind it
  // is exactly the re-wedge this check exists to prevent.
  if (reject_stale_lease(req, conn)) return;
  // W3C trace context from the control plane: when present, per-phase
  // timings (install/exec/collect) are stamped into a `trace` block on the
  // response so the orchestrator can graft them into the request's trace
  // as child spans. Offsets are relative to this request's own start — the
  // two processes' clocks never have to agree.
  std::string traceparent = req.header("traceparent");
  struct timespec t_req;
  clock_gettime(CLOCK_MONOTONIC, &t_req);
  auto since_req = [&t_req]() {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    return (now.tv_sec - t_req.tv_sec) + (now.tv_nsec - t_req.tv_nsec) / 1e9;
  };

  std::string body = conn.read_body();
  minijson::Value parsed;
  try {
    parsed = minijson::parse(body);
  } catch (const std::exception& e) {
    conn.send_response(400, "application/json", "{\"error\":\"bad json\"}");
    return;
  }
  std::string source_code = parsed.get_string("source_code");
  std::string source_file = parsed.get_string("source_file");
  double timeout_s = parsed.get_number("timeout", g_state.default_timeout);
  // Per-request device-memory sampling (the perf-observer plane): only
  // requests that ASK get the runner bracket and the reply block, so the
  // control-plane kill switch keeps the wire byte-for-byte.
  bool want_device_memory = parsed.get_bool("device_memory", false);
  // Purity declaration (the control plane's result memo): echoed back with
  // a hashed result block so a record is verifiable end-to-end. Absent
  // unless declared — the memo kill switch keeps the wire byte-for-byte.
  bool declared_pure = parsed.get_bool("pure", false);
  const minijson::Value& extra_env = parsed.get("env");
  // Per-request resource budget, tighten-only against the APP_LIMIT_* caps.
  // Output is special-cased: the implicit server cap (APP_MAX_OUTPUT_BYTES)
  // keeps its historic TRUNCATE semantics; only an explicit output budget
  // (request body / control-plane lane default) arms the output-cap KILL.
  limits::LimitSpec req_limits = limits::from_json(parsed.get("limits"));
  limits::LimitSpec eff_limits = limits::clamp(req_limits, g_state.limit_caps);
  size_t output_cap = g_state.max_output;
  if (req_limits.output_bytes > 0 &&
      static_cast<size_t>(req_limits.output_bytes) < output_cap) {
    output_cap = static_cast<size_t>(req_limits.output_bytes);
  }
  eff_limits.output_bytes =
      req_limits.output_bytes > 0 ? static_cast<long long>(output_cap) : 0;

  if (source_code.empty() && source_file.empty()) {
    conn.send_response(400, "application/json",
                       "{\"error\":\"source_code or source_file required\"}");
    return;
  }

  std::lock_guard<std::mutex> lock(g_state.exec_mutex);

  // Per-request scratch dir: holds the script (source_code mode) and the
  // stdout/stderr capture files. Never inside the workspace — capture files
  // must not appear in the changed-file diff. Honors TMPDIR so sandboxes
  // with a private scratch tmp (local backend) keep everything inside it —
  // but an unwritable/missing TMPDIR (operator typo, container without the
  // mount) falls back to /tmp with a logged warning instead of failing
  // every request opaquely at mkdtemp.
  std::string tmpdir = env_or("TMPDIR", "/tmp");
  if (tmpdir != "/tmp" && access(tmpdir.c_str(), W_OK | X_OK) != 0) {
    log_msg("TMPDIR %s is not writable (%s); falling back to /tmp",
            tmpdir.c_str(), strerror(errno));
    tmpdir = "/tmp";
  }
  std::string tmpl_s = tmpdir + "/exec-XXXXXX";
  std::vector<char> tmpl(tmpl_s.begin(), tmpl_s.end());
  tmpl.push_back('\0');
  if (!mkdtemp(tmpl.data())) {
    int saved = errno;
    if (tmpdir != "/tmp") {
      // A last-resort retry: the writability probe can race a deletion, or
      // the filesystem can reject mkdtemp for reasons access() can't see.
      log_msg("mkdtemp in %s failed (%s); retrying under /tmp", tmpdir.c_str(),
              strerror(saved));
      tmpl_s = "/tmp/exec-XXXXXX";
      tmpl.assign(tmpl_s.begin(), tmpl_s.end());
      tmpl.push_back('\0');
    }
    if (tmpdir == "/tmp" || !mkdtemp(tmpl.data())) {
      saved = errno;
      minijson::Object err;
      err["error"] = minijson::Value(
          std::string("cannot create scratch dir under ") + tmpdir + ": " +
          strerror(saved) + " (check TMPDIR)");
      conn.send_response(500, "application/json",
                         minijson::Value(err).dump());
      return;
    }
  }
  std::string scratch(tmpl.data());
  std::string script_path;
  auto drop_scratch = [&scratch, &script_path]() {
    if (!script_path.empty()) unlink(script_path.c_str());
    rmdir(scratch.c_str());
  };
  if (!source_code.empty()) {
    script_path = scratch + "/script.py";
    if (!write_file(script_path, source_code)) {
      drop_scratch();
      conn.send_response(500, "application/json", "{\"error\":\"write failed\"}");
      return;
    }
  } else {
    std::string rel = sanitize_rel_path(source_file);
    std::string dup = "workspace/";
    if (rel.compare(0, dup.size(), dup) == 0) rel = rel.substr(dup.size());
    if (rel.empty() || !confine(g_state.workspace, rel, script_path)) {
      drop_scratch();
      conn.send_response(403, "application/json",
                         "{\"error\":\"source_file escapes workspace\"}");
      return;
    }
  }

  // Phase timings for the trace block: install (dependency auto-install +
  // pre-exec workspace snapshot), exec (user code), collect (post-exec
  // snapshot + output read + manifest reconcile).
  double install_start = since_req();
  maybe_install_deps(script_path);

  std::map<std::string, FileSig> before;
  scan_dir(g_state.workspace, "", before);
  // Compile-cache observability: diff the cache dir across the run — new
  // entries are kernels THIS run had to compile (persistent-cache misses
  // made durable), which the control plane harvests and the fleet never
  // compiles again.
  std::map<std::string, FileSig> cc_before;
  if (g_state.compile_cache_enabled)
    scan_dir(g_state.compile_cache_dir, "", cc_before);
  double install_s = since_req() - install_start;

  std::string stdout_path = scratch + "/cap.stdout";
  std::string stderr_path = scratch + "/cap.stderr";

  double exec_start = since_req();
  struct timespec t0, t1;
  clock_gettime(CLOCK_MONOTONIC, &t0);

  RunOutcome run;
  if (!streaming) {
    run = run_user_code(script_path, stdout_path, stderr_path, timeout_s,
                        extra_env, eff_limits, trace_id_of(traceparent),
                        want_device_memory);
  } else {
    // Streaming mode: the run blocks in a worker thread while this thread
    // tails the capture files and pushes NDJSON events over a chunked
    // response. Events: {"stream":"stdout"|"stderr","data":...} chunks,
    // then one final result object (same fields as /execute's body).
    try {
      conn.begin_chunked(200, "application/x-ndjson");
    } catch (const std::exception&) {
      // Client vanished before headers: nothing to stream to. Clean the
      // scratch (submitted source may contain secrets) instead of letting
      // the throw unwind past it, then drop the connection.
      if (source_code.empty()) script_path.clear();  // workspace file: keep
      drop_scratch();
      throw;
    }
    std::atomic<bool> run_done{false};
    std::thread worker([&] {
      // A throw escaping a std::thread calls std::terminate — which would
      // take down the whole sandbox server (warm runner, sessions) for one
      // failed request. Degrade to a failed-run outcome instead, matching
      // the one-connection blast radius of the non-streaming path.
      try {
        run = run_user_code(script_path, stdout_path, stderr_path, timeout_s,
                            extra_env, eff_limits, trace_id_of(traceparent),
                            want_device_memory);
      } catch (const std::exception& e) {
        log_msg("streamed run_user_code threw: %s", e.what());
        run = RunOutcome{};  // exit_code -1, nothing ran warm
      }
      run_done.store(true);
    });
    StreamTail tail_out(stdout_path, "stdout", output_cap);
    StreamTail tail_err(stderr_path, "stderr", output_cap);
    bool client_gone = false;
    while (!run_done.load()) {
      struct timespec ts = {0, 75 * 1000 * 1000};  // 75 ms poll
      nanosleep(&ts, nullptr);
      if (client_gone) continue;  // keep draining the run; stop sending
      try {
        tail_out.pump(conn);
        tail_err.pump(conn);
      } catch (const std::exception&) {
        // Client went away mid-stream: the run must still complete (the
        // runner protocol would desync if we abandoned it mid-request).
        client_gone = true;
      }
    }
    worker.join();
    if (!client_gone) {
      try {
        tail_out.pump(conn);
        tail_err.pump(conn);
      } catch (const std::exception&) {
        client_gone = true;
      }
    }
    // client_gone: the epilogue still runs (scratch cleanup, runner state);
    // sending the final event will just fail silently in its try/catch.
  }

  if (run.multi_host_refused) {
    // A multi-host slice only exists through the warm runner's
    // jax.distributed mesh; a cold subprocess here would run user code
    // with a silently missing mesh — fail loudly instead.
    if (source_code.empty()) script_path.clear();  // workspace file: keep it
    drop_scratch();
    if (!streaming) {
      conn.send_response(500, "application/json",
                         "{\"error\":\"warm runner unavailable on a multi-host "
                         "slice; cannot execute\"}");
    } else {
      try {
        conn.send_chunk(
            "{\"error\":\"warm runner unavailable on a multi-host slice; "
            "cannot execute\"}\n");
        conn.end_chunked();
      } catch (const std::exception&) {
      }
    }
    return;
  }
  int exit_code = run.exit_code;
  bool timed_out = run.timed_out;
  bool runner_died = run.runner_died;
  bool ran_warm = run.ran_warm;
  bool restart_runner = run.restarted;

  clock_gettime(CLOCK_MONOTONIC, &t1);
  double duration =
      (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) / 1e9;

  double collect_start = since_req();
  std::map<std::string, FileSig> after;
  scan_dir(g_state.workspace, "", after);

  // Post-exec quota scan: a filler fast enough to write, exit, and beat the
  // watchdog's next tick still may not hand the next phase an over-quota
  // workspace (the downloads it would trigger are exactly the bytes the
  // quota exists to bound).
  if (run.violation.empty() && eff_limits.disk_bytes > 0 &&
      limits::dir_usage_bytes(g_state.workspace) > eff_limits.disk_bytes) {
    run.violation = limits::kDiskQuota;
  }

  bool out_trunc = false, err_trunc = false;
  std::string out_s = read_file_capped(stdout_path, output_cap, &out_trunc);
  std::string err_s = read_file_capped(stderr_path, output_cap, &err_trunc);
  if (out_trunc) out_s += "\n[stdout truncated]";
  if (err_trunc) err_s += "\n[stderr truncated]";
  if (!run.violation.empty()) {
    std::string note = "Resource limit exceeded: " + run.violation;
    err_s += err_s.empty() ? note : "\n" + note;
  } else if (timed_out) {
    err_s += err_s.empty() ? "Execution timed out" : "\nExecution timed out";
  } else if (runner_died) {
    err_s += err_s.empty() ? "Executor runner crashed" : "\nExecutor runner crashed";
  }
  // Remove the scratch dir (submitted source may contain secrets, and a
  // long-lived dev server must not fill /tmp).
  unlink(stdout_path.c_str());
  unlink(stderr_path.c_str());
  if (source_code.empty()) script_path.clear();  // workspace file: keep it
  drop_scratch();

  minijson::Array files;
  minijson::Array deleted;
  std::vector<std::string> changed_file_shas;
  if (g_state.manifest_enabled) {
    // Changed files carry their content sha so the control plane can skip
    // downloading bytes its content-addressed storage already holds. The
    // manifest is reconciled in the same pass: changed entries rehash (the
    // mtime+size diff already singled them out — this is the "lazy" in lazy
    // rehash), gone entries drop and are reported in `deleted` so a cached
    // client manifest never claims a file the workspace lost.
    std::lock_guard<std::mutex> mlock(g_ws_manifest_mutex);
    for (auto it = g_ws_manifest.begin(); it != g_ws_manifest.end();) {
      if (after.find(it->first) == after.end()) {
        it = g_ws_manifest.erase(it);
      } else {
        ++it;
      }
    }
    for (const auto& rel : diff_snapshots(before, after)) {
      minijson::Object entry;
      entry["path"] = minijson::Value(rel);
      std::string hex;
      FileSig sig;
      if (hash_workspace_file(g_state.workspace, rel, hex, &sig)) {
        g_ws_manifest[rel] = ManifestEntry{hex, sig};
        entry["sha256"] = minijson::Value(hex);
        changed_file_shas.push_back(hex);
      }
      // Hash failure = the file vanished between scan and hash; the entry
      // still reports the path (sans sha) and the download path surfaces
      // the 404 exactly as the pre-manifest protocol did.
      files.push_back(minijson::Value(entry));
    }
    for (const auto& [rel, sig] : before) {
      if (after.find(rel) == after.end()) deleted.push_back(minijson::Value(rel));
    }
  } else {
    for (const auto& rel : diff_snapshots(before, after)) {
      files.push_back(minijson::Value(rel));
    }
  }

  minijson::Object resp;
  resp["stdout"] = minijson::Value(out_s);
  resp["stderr"] = minijson::Value(err_s);
  resp["exit_code"] = minijson::Value(exit_code);
  // Truncation is now a first-class signal (clients previously had to
  // pattern-match the "[stdout truncated]" text); violation carries the
  // typed limit kind when a resource bound ended this run.
  resp["stdout_truncated"] = minijson::Value(out_trunc);
  resp["stderr_truncated"] = minijson::Value(err_trunc);
  if (!run.violation.empty()) resp["violation"] = minijson::Value(run.violation);
  resp["files"] = minijson::Value(files);
  if (g_state.manifest_enabled) resp["deleted"] = minijson::Value(deleted);
  if (g_state.compile_cache_enabled) {
    std::map<std::string, FileSig> cc_after;
    scan_dir(g_state.compile_cache_dir, "", cc_after);
    long long new_entries = 0, new_bytes = 0;
    for (const auto& [rel, sig] : cc_after) {
      if (cc_entry_ignored(rel)) continue;  // jax's local -atime sidecars
      auto it = cc_before.find(rel);
      if (it == cc_before.end() || !(it->second == sig)) {
        ++new_entries;
        new_bytes += sig.size;
      }
    }
    minijson::Object cc;
    cc["new_entries"] = minijson::Value(static_cast<int64_t>(new_entries));
    cc["new_bytes"] = minijson::Value(static_cast<int64_t>(new_bytes));
    cc["entries"] = minijson::Value(static_cast<int64_t>(cc_after.size()));
    if (run.cache_hits >= 0)
      cc["hits"] = minijson::Value(static_cast<int64_t>(run.cache_hits));
    if (run.cache_misses >= 0)
      cc["misses"] = minijson::Value(static_cast<int64_t>(run.cache_misses));
    resp["compile_cache"] = minijson::Value(cc);
  }
  resp["duration_s"] = minijson::Value(duration);
  // The request's device-op wall (the op window around the warm-runner
  // round-trip / cold subprocess): the control plane's chip-second
  // attribution source. Named explicitly so the billing contract does not
  // lean on duration_s keeping its exact semantics forever.
  resp["device_op_seconds"] = minijson::Value(duration);
  // Device-memory accounting (present only when requested AND the warm
  // runner could sample): live/peak device-buffer bytes bracketing the
  // run, plus the runner's RSS — the per-request HBM attribution feed.
  if (run.device_memory.is_object())
    resp["device_memory"] = run.device_memory;
  if (!traceparent.empty()) {
    // The control plane sent trace context: report per-phase timings so it
    // can graft them into the request's trace as child spans. Offsets are
    // seconds since THIS request started on this host (the grafter anchors
    // them to its own span start — no cross-process clock agreement).
    double collect_s = since_req() - collect_start;
    minijson::Object trace;
    trace["traceparent"] = minijson::Value(traceparent);
    minijson::Array trace_spans;
    auto add_span = [&trace_spans](const char* name, double start_offset,
                                   double dur) {
      minijson::Object s;
      s["name"] = minijson::Value(std::string(name));
      s["start_offset_s"] = minijson::Value(start_offset);
      s["duration_s"] = minijson::Value(dur);
      trace_spans.push_back(minijson::Value(s));
    };
    add_span("install", install_start, install_s);
    add_span("exec", exec_start, duration);
    add_span("collect", collect_start, collect_s);
    trace["spans"] = minijson::Value(trace_spans);
    resp["trace"] = minijson::Value(trace);
  }
  resp["warm"] = minijson::Value(ran_warm);
  // True when the warm runner was killed (timeout) or died during this
  // request: its in-process state is gone and a rewarm is in flight. The
  // control plane uses this to end executor_id sessions, whose contract is
  // that the process persists across requests.
  resp["runner_restarted"] = minijson::Value(restart_runner);
  if (declared_pure) {
    resp["pure"] = minijson::Value(true);
    resp["result_sha256"] = minijson::Value(
        pure_result_sha256(out_s, err_s, exit_code, changed_file_shas));
  }
  if (!streaming) {
    conn.send_response(200, "application/json", minijson::Value(resp).dump());
  } else {
    // Final event: the complete /execute response body (chunks were purely
    // additive), so a streaming client needs no second code path to build
    // the result. A vanished client just misses it.
    try {
      conn.send_chunk(minijson::Value(resp).dump() + "\n");
      conn.end_chunked();
    } catch (const std::exception&) {
    }
  }
}

void handle_execute(const minihttp::Request& req, minihttp::Conn& conn) {
  handle_execute_impl(req, conn, /*streaming=*/false);
}

void handle_execute_stream(const minihttp::Request& req,
                           minihttp::Conn& conn) {
  handle_execute_impl(req, conn, /*streaming=*/true);
}

// Monotonic batch-staging counter: each batch's per-job workdirs live under
// a fresh workspace-relative ".batch-<n>" root (exec_mutex serializes
// batches, but a previous batch's dirs persist until /reset — reusing a
// name would make its leftovers look like the new batch's output).
std::atomic<long> g_batch_seq{0};

// POST /execute-batch — the fused half of batched multi-chip execution
// lanes: N compatible small jobs staged into per-job workdirs and run as
// ONE warm-runner dispatch whose job threads spread over the local device
// axis. Per-job stdout/stderr/exit/violation/files come back demuxed; any
// refusal (no warm runner, multi-host slice, old binary's 404) tells the
// control plane to fall back to the serial path.
void handle_execute_batch(const minihttp::Request& req, minihttp::Conn& conn) {
  // Same fencing discipline as /execute: stale claims die before the body
  // read and before exec_mutex.
  if (reject_stale_lease(req, conn)) return;
  std::string traceparent = req.header("traceparent");
  struct timespec t_req;
  clock_gettime(CLOCK_MONOTONIC, &t_req);
  auto since_req = [&t_req]() {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    return (now.tv_sec - t_req.tv_sec) + (now.tv_nsec - t_req.tv_nsec) / 1e9;
  };

  std::string body = conn.read_body();
  minijson::Value parsed;
  try {
    parsed = minijson::parse(body);
  } catch (const std::exception&) {
    conn.send_response(400, "application/json", "{\"error\":\"bad json\"}");
    return;
  }
  const minijson::Value& jobs_v = parsed.get("jobs");
  if (!jobs_v.is_array() || jobs_v.as_array().empty() ||
      jobs_v.as_array().size() > 64) {
    conn.send_response(400, "application/json",
                       "{\"error\":\"jobs must be a non-empty array "
                       "(max 64)\"}");
    return;
  }
  const minijson::Array& jobs = jobs_v.as_array();
  for (const auto& job : jobs) {
    if (job.get_string("source_code").empty()) {
      conn.send_response(400, "application/json",
                         "{\"error\":\"every batch job needs source_code\"}");
      return;
    }
  }
  if (g_state.num_hosts > 1) {
    // A multi-host slice's mesh spans executors; the fused driver runs on
    // one host's runner. The control plane never sends this — refuse
    // loudly rather than run jobs against a silently partial mesh.
    conn.send_response(409, "application/json",
                       "{\"error\":\"batch dispatch unsupported on a "
                       "multi-host slice\"}");
    return;
  }
  if (!g_state.warm_enabled || !g_state.runner) {
    conn.send_response(409, "application/json",
                       "{\"error\":\"batch dispatch requires the warm "
                       "runner\"}");
    return;
  }
  double timeout_s = parsed.get_number("timeout", g_state.default_timeout);
  bool want_device_memory = parsed.get_bool("device_memory", false);
  const minijson::Value& extra_env = parsed.get("env");
  // Same output special-casing as /execute: the implicit server cap keeps
  // TRUNCATE semantics; only an explicit output budget arms the watchdog's
  // output-cap KILL (batch-level, like every other fused-run bound).
  limits::LimitSpec req_limits = limits::from_json(parsed.get("limits"));
  limits::LimitSpec eff_limits = limits::clamp(req_limits, g_state.limit_caps);
  size_t output_cap = g_state.max_output;
  if (req_limits.output_bytes > 0 &&
      static_cast<size_t>(req_limits.output_bytes) < output_cap) {
    output_cap = static_cast<size_t>(req_limits.output_bytes);
  }
  eff_limits.output_bytes =
      req_limits.output_bytes > 0 ? static_cast<long long>(output_cap) : 0;

  std::lock_guard<std::mutex> lock(g_state.exec_mutex);

  // Scratch (scripts + capture files) and the workspace-relative staging
  // root holding one PRIVATE workdir per job — the demux unit for changed
  // files. Same TMPDIR fallback discipline as /execute.
  std::string tmpdir = env_or("TMPDIR", "/tmp");
  if (tmpdir != "/tmp" && access(tmpdir.c_str(), W_OK | X_OK) != 0) tmpdir = "/tmp";
  std::string tmpl_s = tmpdir + "/exec-batch-XXXXXX";
  std::vector<char> tmpl(tmpl_s.begin(), tmpl_s.end());
  tmpl.push_back('\0');
  if (!mkdtemp(tmpl.data())) {
    conn.send_response(500, "application/json",
                       "{\"error\":\"cannot create batch scratch dir\"}");
    return;
  }
  std::string scratch(tmpl.data());
  std::string batch_rel = ".batch-" + std::to_string(++g_batch_seq);
  std::string batch_root = g_state.workspace + "/" + batch_rel;
  std::vector<std::string> cleanup_files;
  auto fail = [&](int status, const std::string& message) {
    for (const auto& path : cleanup_files) unlink(path.c_str());
    rmdir(scratch.c_str());
    minijson::Object err;
    err["error"] = minijson::Value(message);
    conn.send_response(status, "application/json",
                       minijson::Value(err).dump());
  };
  if (mkdir(batch_root.c_str(), 0755) != 0) {
    fail(500, "cannot create batch staging root");
    return;
  }

  double install_start = since_req();
  minijson::Array runner_jobs;
  std::vector<std::string> job_rels, job_out_paths, job_err_paths;
  for (size_t i = 0; i < jobs.size(); ++i) {
    std::string job_rel = batch_rel + "/job-" + std::to_string(i);
    std::string job_dir = g_state.workspace + "/" + job_rel;
    if (mkdir(job_dir.c_str(), 0755) != 0) {
      fail(500, "cannot create batch job workdir");
      return;
    }
    std::string script_path = scratch + "/job-" + std::to_string(i) + ".py";
    if (!write_file(script_path, jobs[i].get_string("source_code"))) {
      fail(500, "cannot stage batch job script");
      return;
    }
    cleanup_files.push_back(script_path);
    maybe_install_deps(script_path);
    std::string out_path = scratch + "/job-" + std::to_string(i) + ".stdout";
    std::string err_path = scratch + "/job-" + std::to_string(i) + ".stderr";
    job_rels.push_back(job_rel);
    job_out_paths.push_back(out_path);
    job_err_paths.push_back(err_path);
    cleanup_files.push_back(out_path);
    cleanup_files.push_back(err_path);
    minijson::Object rj;
    rj["source_path"] = minijson::Value(script_path);
    rj["stdout_path"] = minijson::Value(out_path);
    rj["stderr_path"] = minijson::Value(err_path);
    rj["cwd"] = minijson::Value(job_dir);
    std::string job_trace = jobs[i].get_string("trace_id");
    if (!job_trace.empty()) rj["trace_id"] = minijson::Value(job_trace);
    const minijson::Value& device = jobs[i].get("device_index");
    if (device.is_number()) rj["device_index"] = device;
    runner_jobs.push_back(minijson::Value(rj));
  }
  std::map<std::string, FileSig> cc_before;
  if (g_state.compile_cache_enabled)
    scan_dir(g_state.compile_cache_dir, "", cc_before);
  double install_s = since_req() - install_start;

  std::string batch_out = scratch + "/batch.stdout";
  std::string batch_err = scratch + "/batch.stderr";
  cleanup_files.push_back(batch_out);
  cleanup_files.push_back(batch_err);

  minijson::Object reqo;
  reqo["op"] = minijson::Value(std::string("batch"));
  reqo["jobs"] = minijson::Value(runner_jobs);
  reqo["stdout_path"] = minijson::Value(batch_out);
  reqo["stderr_path"] = minijson::Value(batch_err);
  std::string trace_id = trace_id_of(traceparent);
  if (!trace_id.empty()) reqo["trace_id"] = minijson::Value(trace_id);
  if (want_device_memory) reqo["device_memory"] = minijson::Value(true);
  if (extra_env.is_object()) reqo["env"] = extra_env;
  if (eff_limits.any()) reqo["limits"] = runner_limits_json(eff_limits);

  double exec_start = since_req();
  bool timed_out = false, runner_died = false, ran_warm = false;
  bool restart_runner = false;
  std::string batch_violation;
  minijson::Value runner_resp;
  long long cache_hits = -1, cache_misses = -1;
  {
    // Same warm-up wait discipline as run_user_code; but a batch NEVER
    // falls back to a cold subprocess — there is no per-job isolation
    // story there, and the control plane's serial fallback is strictly
    // better.
    {
      std::unique_lock<std::mutex> wl(g_warm_transition_mutex);
      g_warm_cv.wait(wl, [] {
        return g_warm_state.load() != kWarmPending || g_ever_ready.load();
      });
    }
    if (g_warm_state.load() != kWarmReady) {
      fail(409, "warm runner not ready for batch dispatch");
      return;
    }
    std::lock_guard<std::mutex> rlock(g_state.runner_mutex);
    if (!g_state.runner->alive()) {
      g_warm_state = kWarmFailed;
      start_warm_async();
      fail(409, "warm runner not alive for batch dispatch");
      return;
    }
    // The watchdog watches EVERY capture file of the fused run: each job's
    // private stdout/stderr (where the per-thread stream demux routes
    // Python-level output) plus the batch-level pair (fd-level writes). An
    // explicit output budget is a batch-level bound over their sum, like
    // cpu_time — the serial rerun after an output_cap kill gives the real
    // offender its individual verdict.
    std::vector<std::string> capture_paths = job_out_paths;
    capture_paths.insert(capture_paths.end(), job_err_paths.begin(),
                         job_err_paths.end());
    capture_paths.push_back(batch_out);
    capture_paths.push_back(batch_err);
    limits::Watchdog wd(eff_limits, g_state.runner->pid(), g_state.workspace,
                        capture_paths, g_state.limit_poll_interval);
    wd.start();
    // Same kernel-event bracket as the serial warm path: a cgroup OOM/
    // fork-refusal during the fused run is a BATCH-level violation (the
    // group is shared), reclassified below.
    g_runner_scope.refresh_baseline();
    WarmRunner::ExecResult r = g_state.runner->execute(
        minijson::Value(reqo).dump(), timeout_s > 0 ? timeout_s + 0.5 : 0,
        runner_resp, /*allow_interrupt=*/true);
    wd.stop();
    ran_warm = true;
    switch (r) {
      case WarmRunner::ExecResult::kOk:
        batch_violation = runner_resp.get_string("violation", "");
        cache_hits =
            static_cast<long long>(runner_resp.get_number("cache_hits", -1));
        cache_misses =
            static_cast<long long>(runner_resp.get_number("cache_misses", -1));
        break;
      case WarmRunner::ExecResult::kTimeout:
        timed_out = true;
        restart_runner = true;
        break;
      case WarmRunner::ExecResult::kInterrupted:
        // The runner survived the SIGINT, but its job THREADS may not have
        // unwound (signals reach only the main thread) — the next /reset
        // will refuse on surviving threads and the control plane disposes.
        timed_out = true;
        break;
      case WarmRunner::ExecResult::kDied:
        runner_died = true;
        restart_runner = true;
        break;
    }
    std::string wd_kind = wd.violation();
    if (!wd_kind.empty()) batch_violation = wd_kind;
    if (batch_violation.empty()) {
      const char* cg_kind = g_runner_scope.violation();
      if (cg_kind) batch_violation = cg_kind;
    }
    if (restart_runner) {
      g_warm_state = kWarmFailed;
      start_warm_async();
    }
  }
  double exec_s = since_req() - exec_start;

  // Post-exec disk-quota scan over the whole workspace (the batch root is
  // inside it), batch-level like every other group bound.
  if (batch_violation.empty() && eff_limits.disk_bytes > 0 &&
      limits::dir_usage_bytes(g_state.workspace) > eff_limits.disk_bytes) {
    batch_violation = limits::kDiskQuota;
  }

  double collect_start = since_req();
  const minijson::Value& job_results = runner_resp.get("jobs");
  minijson::Array results;
  minijson::Array trace_spans;
  for (size_t i = 0; i < jobs.size(); ++i) {
    minijson::Object entry;
    entry["workdir"] = minijson::Value(job_rels[i]);
    int exit_code = -1;
    double job_duration = 0.0, job_offset = 0.0;
    std::string job_violation;
    bool aborted = timed_out || runner_died;
    if (job_results.is_array() && i < job_results.as_array().size()) {
      const minijson::Value& jr = job_results.as_array()[i];
      exit_code = static_cast<int>(jr.get_number("exit_code", -1));
      job_duration = jr.get_number("duration_s", 0.0);
      job_offset = jr.get_number("start_offset_s", 0.0);
      job_violation = jr.get_string("violation", "");
      aborted = aborted || jr.get_bool("aborted", false);
      // Per-job device-memory bracket (best-effort under concurrent
      // batchmates — one address space; the wire shape matches /execute's
      // block so the demux path parses once).
      if (jr.get("device_memory").is_object())
        entry["device_memory"] = jr.get("device_memory");
    }
    bool out_trunc = false, err_trunc = false;
    std::string out_s =
        read_file_capped(job_out_paths[i], output_cap, &out_trunc);
    std::string err_s =
        read_file_capped(job_err_paths[i], output_cap, &err_trunc);
    if (out_trunc) out_s += "\n[stdout truncated]";
    if (err_trunc) err_s += "\n[stderr truncated]";
    if (!job_violation.empty()) {
      std::string note = "Resource limit exceeded: " + job_violation;
      err_s += err_s.empty() ? note : "\n" + note;
    }
    entry["stdout"] = minijson::Value(out_s);
    entry["stderr"] = minijson::Value(err_s);
    entry["exit_code"] = minijson::Value(exit_code);
    entry["stdout_truncated"] = minijson::Value(out_trunc);
    entry["stderr_truncated"] = minijson::Value(err_trunc);
    entry["duration_s"] = minijson::Value(job_duration);
    // Per-job device-op seconds: the job thread's own exec span inside the
    // fused run — the weight the control plane apportions the dispatch's
    // chip-seconds by (usage metering; duplicates duration_s today, named
    // separately so the attribution contract survives if duration_s ever
    // grows non-device phases).
    entry["device_op_seconds"] = minijson::Value(job_duration);
    entry["start_offset_s"] = minijson::Value(exec_start + job_offset);
    if (!job_violation.empty())
      entry["violation"] = minijson::Value(job_violation);
    if (aborted) entry["aborted"] = minijson::Value(true);
    // Changed files = everything in the job's private workdir (created
    // fresh for this batch), reported RELATIVE to it so the control plane
    // can demux each caller's files to the paths its code wrote.
    minijson::Array files;
    std::vector<std::string> job_file_shas;
    std::map<std::string, FileSig> job_files;
    scan_dir(g_state.workspace + "/" + job_rels[i], "", job_files);
    for (const auto& [rel, sig] : job_files) {
      minijson::Object fe;
      fe["path"] = minijson::Value(rel);
      if (g_state.manifest_enabled) {
        std::string full_rel = job_rels[i] + "/" + rel;
        std::string hex;
        FileSig hashed;
        if (hash_workspace_file(g_state.workspace, full_rel, hex, &hashed)) {
          std::lock_guard<std::mutex> mlock(g_ws_manifest_mutex);
          g_ws_manifest[full_rel] = ManifestEntry{hex, hashed};
          fe["sha256"] = minijson::Value(hex);
          job_file_shas.push_back(hex);
        }
      }
      files.push_back(minijson::Value(fe));
    }
    entry["files"] = minijson::Value(files);
    if (jobs[i].get_bool("pure", false)) {
      // Per-job purity echo, hashed over THIS entry's demuxed streams and
      // files — a batchmate's output can never slip into a recorded
      // result unnoticed.
      entry["pure"] = minijson::Value(true);
      entry["result_sha256"] = minijson::Value(
          pure_result_sha256(out_s, err_s, exit_code, job_file_shas));
    }
    results.push_back(minijson::Value(entry));
    if (!traceparent.empty()) {
      minijson::Object s;
      s["name"] = minijson::Value("job-" + std::to_string(i));
      s["start_offset_s"] = minijson::Value(exec_start + job_offset);
      s["duration_s"] = minijson::Value(job_duration);
      trace_spans.push_back(minijson::Value(s));
    }
  }
  // Read the batch-level captures BEFORE the scratch cleanup unlinks them.
  // Batch-level STDOUT means fd-level writes (a subprocess, a C extension)
  // bypassed the per-thread demux: surface it so the control plane can
  // refuse the demux and rerun serially — output the serial path returns
  // must never be silently dropped.
  bool stray_trunc = false;
  std::string stray_err = read_file_capped(batch_err, 64 * 1024, &stray_trunc);
  std::string stray_out = read_file_capped(batch_out, 64 * 1024, &stray_trunc);
  for (const auto& path : cleanup_files) unlink(path.c_str());
  rmdir(scratch.c_str());

  minijson::Object resp;
  resp["results"] = minijson::Value(results);
  resp["warm"] = minijson::Value(ran_warm);
  resp["runner_restarted"] = minijson::Value(restart_runner);
  // The fused dispatch's device-op wall, from this server's own op window
  // (the whole runner round-trip): what the batch actually held the
  // devices for — the control plane's chip-second attribution source
  // (per-job shares are apportioned by the entries' device_op_seconds).
  resp["device_op_seconds"] = minijson::Value(exec_s);
  if (timed_out) resp["timed_out"] = minijson::Value(true);
  if (!batch_violation.empty())
    resp["violation"] = minijson::Value(batch_violation);
  if (!stray_err.empty()) resp["batch_stderr"] = minijson::Value(stray_err);
  if (!stray_out.empty()) resp["batch_stdout"] = minijson::Value(stray_out);
  if (g_state.compile_cache_enabled) {
    std::map<std::string, FileSig> cc_after;
    scan_dir(g_state.compile_cache_dir, "", cc_after);
    long long new_entries = 0, new_bytes = 0;
    for (const auto& [rel, sig] : cc_after) {
      if (cc_entry_ignored(rel)) continue;
      auto it = cc_before.find(rel);
      if (it == cc_before.end() || !(it->second == sig)) {
        ++new_entries;
        new_bytes += sig.size;
      }
    }
    minijson::Object cc;
    cc["new_entries"] = minijson::Value(static_cast<int64_t>(new_entries));
    cc["new_bytes"] = minijson::Value(static_cast<int64_t>(new_bytes));
    cc["entries"] = minijson::Value(static_cast<int64_t>(cc_after.size()));
    if (cache_hits >= 0)
      cc["hits"] = minijson::Value(static_cast<int64_t>(cache_hits));
    if (cache_misses >= 0)
      cc["misses"] = minijson::Value(static_cast<int64_t>(cache_misses));
    resp["compile_cache"] = minijson::Value(cc);
  }
  if (!traceparent.empty()) {
    double collect_s = since_req() - collect_start;
    minijson::Object trace;
    trace["traceparent"] = minijson::Value(traceparent);
    minijson::Object s_install;
    s_install["name"] = minijson::Value(std::string("install"));
    s_install["start_offset_s"] = minijson::Value(install_start);
    s_install["duration_s"] = minijson::Value(install_s);
    trace_spans.push_back(minijson::Value(s_install));
    minijson::Object s_exec;
    s_exec["name"] = minijson::Value(std::string("exec"));
    s_exec["start_offset_s"] = minijson::Value(exec_start);
    s_exec["duration_s"] = minijson::Value(exec_s);
    trace_spans.push_back(minijson::Value(s_exec));
    minijson::Object s_collect;
    s_collect["name"] = minijson::Value(std::string("collect"));
    s_collect["start_offset_s"] = minijson::Value(collect_start);
    s_collect["duration_s"] = minijson::Value(collect_s);
    trace_spans.push_back(minijson::Value(s_collect));
    trace["spans"] = minijson::Value(trace_spans);
    resp["trace"] = minijson::Value(trace);
  }
  conn.send_response(200, "application/json", minijson::Value(resp).dump());
}

minijson::Value warm_status_body() {
  minijson::Object resp;
  resp["status"] = minijson::Value("ok");
  int state = g_warm_state.load();
  bool warm = state == kWarmReady && g_state.runner && g_state.runner->alive();
  resp["warm"] = minijson::Value(warm);
  resp["warm_state"] = minijson::Value(std::string(warm_state_name(state)));
  if (warm) {
    resp["backend"] = minijson::Value(g_state.runner->backend());
    resp["device_count"] = minijson::Value(g_state.runner->device_count());
  }
  // Which limits-enforcement mode this sandbox ACTUALLY runs in: cgroup-v2
  // hard caps (memory.max/pids.max armed), or the rlimits+watchdog
  // fallback and why. The control plane, operators, and the test suite's
  // auto-skip all read this instead of guessing at the host's cgroup
  // posture.
  {
    minijson::Object cg;
    cg["enforced"] = minijson::Value(g_cgroup.enabled);
    if (g_cgroup.enabled) {
      cg["base"] = minijson::Value(g_cgroup.base);
      cg["runner_scope"] = minijson::Value(g_runner_scope.active());
    } else {
      cg["fallback_reason"] = minijson::Value(g_cgroup.reason);
    }
    resp["cgroup"] = minijson::Value(cg);
  }
  return minijson::Value(resp);
}

void handle_healthz(const minihttp::Request&, minihttp::Conn& conn) {
  // Liveness + warm telemetry: always 200 while the server is up; the body
  // carries warm_state so the control plane can poll init progress.
  conn.send_response(200, "application/json", warm_status_body().dump());
}

// GET /device-stats — the raw device-health signals the control plane's
// probe daemon classifies into healthy/busy/suspect/wedged. DELIBERATELY
// lock-free (atomics + one tiny string mutex never held across I/O): it
// must answer while exec_mutex/runner_mutex are pinned by a wedged device
// op — the exact situation where /healthz kept saying "ok" while attaches
// blocked 50-76 minutes (BENCH_r03-r05). Ages are computed server-side on
// the server's own monotonic clock, so the probe never does cross-host
// clock math.
void handle_device_stats(const minihttp::Request&, minihttp::Conn& conn) {
  long long now = now_ms();
  minijson::Object resp;
  resp["status"] = minijson::Value(std::string("ok"));
  int state = g_warm_state.load();
  resp["warm_state"] = minijson::Value(std::string(warm_state_name(state)));
  resp["warm"] =
      minijson::Value(state == kWarmReady && g_runner_ready_stat.load());
  {
    std::lock_guard<std::mutex> dlock(g_device_info_mutex);
    resp["backend"] = minijson::Value(g_device_backend_stat);
    resp["device_kind"] = minijson::Value(g_device_kind_stat);
  }
  resp["device_count"] = minijson::Value(g_device_count_stat.load());
  resp["num_hosts"] = minijson::Value(g_state.num_hosts);
  resp["uptime_s"] = minijson::Value((now - g_boot_ms.load()) / 1000.0);
  // Attach telemetry: pending age while a warm-up (jax import + device
  // attach) is in flight, plus the last successful attach's latency.
  long long attach_start = g_attach_start_ms.load();
  resp["attach_pending_s"] = minijson::Value(
      attach_start > 0 ? (now - attach_start) / 1000.0 : 0.0);
  long long attach_last = g_attach_last_ms.load();
  resp["attach_seconds"] =
      minijson::Value(attach_last >= 0 ? attach_last / 1000.0 : -1.0);
  // Current device op (warm-runner round-trip): age + declared budget.
  long long op_start = g_op_start_ms.load();
  resp["op_in_flight"] = minijson::Value(op_start > 0);
  resp["op_age_s"] =
      minijson::Value(op_start > 0 ? (now - op_start) / 1000.0 : 0.0);
  resp["op_timeout_s"] = minijson::Value(
      op_start > 0 ? g_op_timeout_ms.load() / 1000.0 : 0.0);
  long long last_ok = g_last_op_ok_ms.load();
  resp["last_device_op_age_s"] =
      minijson::Value(last_ok > 0 ? (now - last_ok) / 1000.0 : -1.0);
  long long line = g_runner_line_ms.load();
  resp["runner_heartbeat_age_s"] =
      minijson::Value(line > 0 ? (now - line) / 1000.0 : -1.0);
  long long runner_pid = g_runner_pid_stat.load();
  bool runner_alive = g_runner_ready_stat.load();
  if (runner_alive && runner_pid > 0) {
    // The ready mirror goes stale when the runner dies SILENTLY (OOM kill
    // between requests): nothing notices until the next execute finds the
    // corpse. Peek at the child without reaping it (WNOWAIT — kill_runner's
    // waitpid still collects the zombie), so the probe sees a dead-idle
    // runner instead of an eternally "healthy" host.
    siginfo_t info;
    info.si_pid = 0;
    if (waitid(P_PID, static_cast<id_t>(runner_pid), &info,
               WEXITED | WNOHANG | WNOWAIT) == 0 &&
        info.si_pid == static_cast<pid_t>(runner_pid)) {
      runner_alive = false;
    }
  }
  resp["runner_alive"] = minijson::Value(runner_alive);
  resp["runner_pid"] = minijson::Value(static_cast<double>(runner_pid));
  if (!g_state.lease_require_token) {
    // The held lease token: lets an operator (or the probe) see which
    // generation this server will honor without sending a claim. REDACTED
    // in strict mode — there, possession of the token IS the dispatch
    // credential, and this route is as reachable from inside the sandbox
    // as /execute (strict operators read the boot/refusal logs instead).
    std::lock_guard<std::mutex> llock(g_lease_mutex);
    resp["lease_token"] = minijson::Value(g_lease_token);
  }
  resp["rss_bytes"] = minijson::Value(
      static_cast<double>(rss_bytes_of(static_cast<long long>(getpid()))));
  resp["runner_rss_bytes"] = minijson::Value(
      static_cast<double>(runner_pid > 0 ? rss_bytes_of(runner_pid) : -1));
  conn.send_response(200, "application/json", minijson::Value(resp).dump());
}

void handle_readyz(const minihttp::Request&, minihttp::Conn& conn) {
  // Readiness: 503 until the sandbox can actually serve its purpose (warm
  // runner hot, or warm mode off). This is what k8s readinessProbe targets,
  // so "pod Ready" still means "TPU hot" without the server's *existence*
  // depending on TPU init (the r01 failure mode).
  bool ready = !g_state.warm_enabled || g_warm_state.load() == kWarmReady;
  conn.send_response(ready ? 200 : 503, "application/json",
                     warm_status_body().dump());
}

void handle_warmup(const minihttp::Request&, minihttp::Conn& conn) {
  conn.drain_body();
  start_warm_async();
  conn.send_response(200, "application/json", warm_status_body().dump());
}

// POST /reset — generation turnover: scrub the warm runner (stray children,
// env, workspace modules) and wipe workspace + runtime-packages, keeping the
// process and its TPU lease alive. 409 ⇒ not scrubbable (runner cold, mid-
// rewarm after a timeout kill, or reset failed); the control plane must then
// dispose the whole sandbox instead of reusing it. This is the mechanism that
// separates the chip lease from the disposable sandbox: single-use WORKSPACE,
// reusable DEVICE PROCESS (reference pods pay a full respawn here,
// kubernetes_code_executor.py:263-279 — a fresh pod per request).
void handle_reset(const minihttp::Request& req, minihttp::Conn& conn) {
  // A /reset from a fenced predecessor's control path (a retry racing a
  // dispose) must not wipe the successor's workspace mid-request.
  if (reject_stale_lease(req, conn)) return;
  conn.drain_body();
  std::lock_guard<std::mutex> lock(g_state.exec_mutex);
  auto refuse = [&conn](const char* reason) {
    minijson::Object resp;
    resp["ok"] = minijson::Value(false);
    resp["reason"] = minijson::Value(std::string(reason));
    conn.send_response(409, "application/json", minijson::Value(resp).dump());
  };
  if (g_state.warm_enabled && g_state.runner) {
    if (g_warm_state.load() != kWarmReady) {
      refuse("runner not warm");
      return;
    }
    std::lock_guard<std::mutex> rlock(g_state.runner_mutex);
    if (!g_state.runner->alive() || !g_state.runner->reset(8.0)) {
      {
        std::lock_guard<std::mutex> l(g_warm_transition_mutex);
        g_warm_state = kWarmFailed;
      }
      g_warm_cv.notify_all();
      refuse("runner reset failed");
      return;
    }
  }
  // Runner scrubbed first (strays that could still write files are dead),
  // then the filesystem: workspace AND runtime-packages — a package the
  // previous user planted must never be importable by the next one. The
  // compilation-cache subtree is preserved EVERYWHERE: compiled XLA
  // kernels are the one cross-generation state turnover deliberately
  // keeps, and the historic layout put the cache dir under /tmp, squarely
  // inside the k8s backend's APP_RESET_EXTRA_WIPE_DIRS. Preservation is a
  // trust decision, not a no-op: entries CAN hold tenant-influenced bytes
  // (user code can write the dir; XLA constant-folding can bake input
  // data into artifacts), which is why the control plane only ever
  // harvests sandboxes that never ran tenant code — the preserved dir
  // stays pod-local state, never fleet state.
  // Gated on the kill switch: APP_COMPILE_CACHE=0 must restore EXACT
  // pre-cache reset behavior — a preserved-but-unserved cache dir would
  // keep the one cross-generation channel the switch exists to close.
  const std::string preserve =
      g_state.compile_cache_enabled ? g_state.compile_cache_dir
                                    : std::string();
  if (!wipe_dir_children(g_state.workspace, preserve) ||
      !wipe_dir_children(g_state.runtime_packages, preserve)) {
    refuse("workspace wipe incomplete");
    return;
  }
  for (const auto& dir : g_state.extra_wipe_dirs) {
    struct stat st;
    if (stat(dir.c_str(), &st) != 0) continue;  // absent dir leaks nothing
    if (!wipe_dir_children(dir, preserve)) {
      refuse("extra wipe dir incomplete");
      return;
    }
  }
  // The workspace is empty now: a stale manifest would let a conditional
  // upload from the NEXT generation 304 against content the wipe removed.
  {
    std::lock_guard<std::mutex> mlock(g_ws_manifest_mutex);
    g_ws_manifest.clear();
  }
  minijson::Value status = warm_status_body();
  status.as_object()["ok"] = minijson::Value(true);
  conn.send_response(200, "application/json", status.dump());
}

// POST /snapshot and POST /restore — session durability: relay an
// interpreter-state op over the warm-runner pipe. The workspace BYTES never
// ride these routes (they ride the existing manifest-negotiated PUT/GET
// paths, so an unchanged workspace moves zero bytes); this is only the
// serialized interpreter state (env deltas, cwd, workspace-module globals).
// 409 ⇒ no warm runner to snapshot/restore (cold, mid-rewarm, or the op
// failed and killed it); the control plane treats that as "recreate fresh",
// never as a half-restored session.
void handle_snapshot_op(const minihttp::Request& req, minihttp::Conn& conn,
                        bool is_restore) {
  // Same fencing discipline as /reset: a fenced predecessor's control path
  // must not snapshot (or worse, restore into) the successor's runner.
  if (reject_stale_lease(req, conn)) return;
  std::string body = conn.read_body();
  minijson::Value parsed;
  if (!body.empty()) {
    try {
      parsed = minijson::parse(body);
    } catch (const std::exception&) {
      conn.send_response(400, "application/json", "{\"error\":\"bad json\"}");
      return;
    }
  }
  double timeout_s = parsed.get_number("timeout", 30.0);
  std::lock_guard<std::mutex> lock(g_state.exec_mutex);
  auto refuse = [&conn](const char* reason) {
    minijson::Object resp;
    resp["ok"] = minijson::Value(false);
    resp["reason"] = minijson::Value(std::string(reason));
    conn.send_response(409, "application/json", minijson::Value(resp).dump());
  };
  if (!g_state.warm_enabled || !g_state.runner) {
    refuse("no warm runner");
    return;
  }
  if (g_warm_state.load() != kWarmReady) {
    refuse("runner not warm");
    return;
  }
  minijson::Object op;
  if (is_restore) {
    op["op"] = minijson::Value(std::string("restore"));
    op["state"] = parsed.get("state");
  } else {
    op["op"] = minijson::Value(std::string("snapshot"));
    double max_bytes = parsed.get_number("max_bytes", 0.0);
    if (max_bytes > 0) op["max_bytes"] = minijson::Value(max_bytes);
  }
  std::lock_guard<std::mutex> rlock(g_state.runner_mutex);
  minijson::Value response;
  if (!g_state.runner->alive() ||
      g_state.runner->execute(minijson::Value(op).dump(), timeout_s,
                              response) != WarmRunner::ExecResult::kOk) {
    // The op killed the runner (timeout/death): same state machine as a
    // failed reset — this sandbox can no longer be trusted warm.
    {
      std::lock_guard<std::mutex> l(g_warm_transition_mutex);
      g_warm_state = kWarmFailed;
    }
    g_warm_cv.notify_all();
    refuse(is_restore ? "runner restore failed" : "runner snapshot failed");
    return;
  }
  conn.send_response(200, "application/json", response.dump());
}

void handle_snapshot(const minihttp::Request& req, minihttp::Conn& conn) {
  handle_snapshot_op(req, conn, /*is_restore=*/false);
}

void handle_restore(const minihttp::Request& req, minihttp::Conn& conn) {
  handle_snapshot_op(req, conn, /*is_restore=*/true);
}

void route(const minihttp::Request& req, minihttp::Conn& conn) {
  if (req.method == "POST" && req.target == "/execute") {
    handle_execute(req, conn);
  } else if (req.method == "POST" && req.target == "/execute-batch") {
    handle_execute_batch(req, conn);
  } else if (req.method == "POST" && req.target == "/execute/stream") {
    handle_execute_stream(req, conn);
  } else if (req.method == "POST" && req.target == "/warmup") {
    handle_warmup(req, conn);
  } else if (req.method == "POST" && req.target == "/reset") {
    handle_reset(req, conn);
  } else if (req.method == "POST" && req.target == "/snapshot") {
    handle_snapshot(req, conn);
  } else if (req.method == "POST" && req.target == "/restore") {
    handle_restore(req, conn);
  } else if (req.method == "POST" && req.target == "/lease") {
    handle_lease(req, conn);
  } else if (req.method == "GET" && req.target == "/workspace-manifest") {
    handle_manifest(req, conn);
  } else if (req.method == "GET" && req.target == "/compile-cache-manifest") {
    handle_cc_manifest(req, conn);
  } else if (req.method == "GET" && req.target == "/healthz") {
    handle_healthz(req, conn);
  } else if (req.method == "GET" && req.target == "/device-stats") {
    handle_device_stats(req, conn);
  } else if (req.method == "GET" && req.target == "/readyz") {
    handle_readyz(req, conn);
  } else if (req.method == "PUT") {
    handle_upload(req, conn);
  } else if (req.method == "GET" || req.method == "HEAD") {
    handle_download(req, conn);
  } else {
    conn.drain_body();
    conn.send_response(404, "application/json", "{\"error\":\"no route\"}");
  }
}

std::string self_dir() {
  char buf[PATH_MAX];
  ssize_t n = readlink("/proc/self/exe", buf, sizeof(buf) - 1);
  if (n <= 0) return ".";
  buf[n] = 0;
  std::string p(buf);
  size_t slash = p.rfind('/');
  return slash == std::string::npos ? "." : p.substr(0, slash);
}

}  // namespace

int main() {
  g_boot_ms = now_ms();
  std::string listen_addr = env_or("APP_LISTEN_ADDR", "0.0.0.0:8000");
  g_state.workspace = env_or("APP_WORKSPACE", "/workspace");
  g_state.runtime_packages = env_or("APP_RUNTIME_PACKAGES", "/runtime-packages");
  g_state.python = env_or("APP_PYTHON", "python3");
  std::string exe_dir = self_dir();
  auto sibling = [&exe_dir](const std::string& name) {
    std::string p = exe_dir + "/" + name;
    if (access(p.c_str(), R_OK) == 0) return p;
    return exe_dir + "/../" + name;  // binary lives in build/, scripts beside it
  };
  g_state.runner_script = env_or("APP_RUNNER_SCRIPT", sibling("runner.py"));
  g_state.deps_script = env_or("APP_DEPS_SCRIPT", sibling("deps.py"));
  g_state.launch_script = env_or("APP_LAUNCH_SCRIPT", sibling("launch.py"));
  g_state.warm_enabled = env_flag("APP_WARM_RUNNER", true);
  g_state.warm_eager = env_flag("APP_WARM_EAGER", true);
  g_state.auto_install = env_flag("APP_AUTO_INSTALL_DEPS", false);
  g_state.manifest_enabled = env_flag("APP_WORKSPACE_MANIFEST", true);
  {
    // The fleet compile cache serves the same dir JAX writes its
    // persistent compilation cache to; no dir (or APP_COMPILE_CACHE=0)
    // removes the routes AND the reset-wipe exclusion.
    std::string cc = env_or("JAX_COMPILATION_CACHE_DIR", "");
    while (cc.size() > 1 && cc.back() == '/') cc.pop_back();
    g_state.compile_cache_dir = cc;
    g_state.compile_cache_enabled =
        !cc.empty() && env_flag("APP_COMPILE_CACHE", true);
    if (g_state.compile_cache_enabled) {
      // mkdir -p: the dir may be several levels deep (the default lives
      // under /var/tmp/<service>/) and must exist before the first seed
      // PUT or manifest GET lands.
      std::string partial;
      for (size_t i = 0; i <= cc.size(); ++i) {
        char c = i < cc.size() ? cc[i] : '/';
        if (c == '/' && !partial.empty()) mkdir(partial.c_str(), 0777);
        partial += c;
      }
    }
  }
  {
    std::string dirs = env_or("APP_RESET_EXTRA_WIPE_DIRS", "");
    std::string home = env_or("HOME", "");
    std::string cur;
    for (size_t i = 0; i <= dirs.size(); ++i) {
      char c = i < dirs.size() ? dirs[i] : ':';
      if (c == ':') {
        if (!cur.empty()) {
          if (cur[0] == '~' && !home.empty()) cur = home + cur.substr(1);
          g_state.extra_wipe_dirs.push_back(cur);
        }
        cur.clear();
      } else {
        cur += c;
      }
    }
  }
  g_state.num_hosts = static_cast<int>(env_num("APP_NUM_HOSTS", 1));
  // Local-subprocess backend sets this so a SIGKILLed control plane can't
  // orphan sandboxes. SIGTERM (not SIGKILL) so the shutdown handler below
  // still reaps the runner's session. Off in pods, where the server is the
  // container's PID 1 and GC is the ownerReference's job.
  if (env_flag("APP_PARENT_DEATH_EXIT", false)) {
    prctl(PR_SET_PDEATHSIG, SIGTERM);
  }
  g_state.default_timeout = env_num("APP_DEFAULT_TIMEOUT", 60.0);
  g_state.max_output = static_cast<size_t>(env_num("APP_MAX_OUTPUT_BYTES", 10485760));
  g_state.limit_caps = limits::caps_from_env();
  g_state.limit_poll_interval = env_num("APP_LIMIT_POLL_INTERVAL", 0.1);
  g_state.lease_require_token = env_flag("APP_LEASE_REQUIRE_TOKEN", false);
  if (g_state.lease_require_token)
    log_msg("strict lease mode: tokenless dispatches 409 once leased");
  // cgroup-v2 hard enforcement: detect a writable, memory+pids-delegated
  // v2 subtree (the one this process lives in, or APP_CGROUP_ROOT) and
  // park the warm runner group in a caps-bounded scope. Every failure
  // mode — v1/hybrid host, read-only cgroupfs, shared subtree, kill
  // switch — falls back cleanly to the rlimits+watchdog layers alone.
  g_cgroup = cgroup::init(env_flag("APP_CGROUP_ENFORCE", true));
  if (g_cgroup.enabled) {
    long long cap_mem = g_state.limit_caps.memory_bytes;
    long long cap_nproc = g_state.limit_caps.nproc;
    if (cap_mem > 0 || cap_nproc > 0) {
      // The runner scope bounds the SANDBOX for its whole life with the
      // boot caps (per-request tighten-only overrides stay the watchdog's
      // job). memory_bytes means "beyond the warm baseline", and a cgroup
      // counts from zero — the headroom absorbs the runner's own RSS
      // (jax + libtpu can be GiBs on real devices; tune per deployment).
      // The pids headroom covers the runner's interpreter/runtime threads
      // (the pids controller counts tasks, threads included).
      long long headroom = static_cast<long long>(
          env_num("APP_CGROUP_RUNNER_HEADROOM_BYTES", 2147483648.0));
      g_runner_scope = cgroup::Scope::create(
          g_cgroup, "runner", cap_mem > 0 ? cap_mem + headroom : 0,
          cap_nproc > 0 ? cap_nproc + 512 : 0);
      if (g_runner_scope.active())
        g_runner_cgroup_procs = g_runner_scope.procs_path();
    }
    log_msg("cgroup-v2 enforcement armed (base=%s runner_scope=%d)",
            g_cgroup.base.c_str(), (int)g_runner_scope.active());
  } else {
    log_msg("cgroup-v2 enforcement unavailable (%s); rlimits+watchdog only",
            g_cgroup.reason.c_str());
  }
  if (g_state.limit_caps.any()) {
    log_msg(
        "resource limits armed: mem=%lld cpu=%.0fs nproc=%lld nofile=%lld "
        "fsize=%lld disk=%lld (0 = off)",
        g_state.limit_caps.memory_bytes, g_state.limit_caps.cpu_seconds,
        g_state.limit_caps.nproc, g_state.limit_caps.nofile,
        g_state.limit_caps.fsize_bytes, g_state.limit_caps.disk_bytes);
  }

  mkdir(g_state.workspace.c_str(), 0777);
  mkdir(g_state.runtime_packages.c_str(), 0777);

  // Graceful shutdown (kubelet pod stop, local backend teardown): reap the
  // runner's whole session, then exit.
  struct sigaction sa {};
  sa.sa_handler = handle_shutdown_signal;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);

  if (!g_state.warm_enabled && g_state.num_hosts > 1) {
    // A multi-host slice only exists through the warm runner's
    // jax.distributed mesh — refusing a misconfigured boot beats presenting
    // a sandbox whose user code silently sees no mesh.
    log_msg("APP_NUM_HOSTS>1 requires the warm runner; exiting");
    return 1;
  }
  double ready_timeout = env_num("APP_RUNNER_READY_TIMEOUT", 180.0);
  WarmRunner runner(g_state.python, g_state.runner_script, g_state.workspace,
                    ready_timeout);
  if (g_state.warm_enabled) g_state.runner = &runner;

  // Announce the port BEFORE any TPU init: "reachable" must not wait on
  // "hot". Warm-up runs on a background thread (eager mode) or when the
  // control plane POSTs /warmup after acquiring its per-chip lease.
  minihttp::Server server(listen_addr, route);
  printf("LISTENING port=%d\n", server.port());
  fflush(stdout);
  log_msg("executor-server listening on port %d (workspace=%s warm=%d eager=%d)",
          server.port(), g_state.workspace.c_str(), (int)g_state.warm_enabled,
          (int)g_state.warm_eager);
  if (g_state.warm_enabled && g_state.warm_eager) start_warm_async();
  server.serve_forever();
}
