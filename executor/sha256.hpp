// Streaming SHA-256 (FIPS 180-4), dependency-free, for the workspace
// manifest: uploads hash as their bytes land on disk and the post-execute
// scan rehashes only entries whose size/mtime changed. The digest hex IS the
// control plane's storage object id (services/storage.py names objects by
// content sha), which is what makes hash negotiation possible at all — both
// sides speak the same identifier without ever exchanging file bytes.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

namespace minisha {

class Sha256 {
 public:
  Sha256() { reset(); }

  void reset() {
    state_[0] = 0x6a09e667u; state_[1] = 0xbb67ae85u;
    state_[2] = 0x3c6ef372u; state_[3] = 0xa54ff53au;
    state_[4] = 0x510e527fu; state_[5] = 0x9b05688cu;
    state_[6] = 0x1f83d9abu; state_[7] = 0x5be0cd19u;
    total_ = 0;
    buf_len_ = 0;
  }

  void update(const void* data, size_t len) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    total_ += len;
    if (buf_len_ > 0) {
      size_t take = 64 - buf_len_;
      if (take > len) take = len;
      memcpy(buf_ + buf_len_, p, take);
      buf_len_ += take;
      p += take;
      len -= take;
      if (buf_len_ == 64) {
        compress(buf_);
        buf_len_ = 0;
      }
    }
    while (len >= 64) {
      compress(p);
      p += 64;
      len -= 64;
    }
    if (len > 0) {
      memcpy(buf_, p, len);
      buf_len_ = len;
    }
  }

  // Finalizes and returns the lowercase hex digest. The object may not be
  // reused afterwards without reset().
  std::string hex() {
    uint64_t bit_len = total_ * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len_ != 56) update(&zero, 1);
    uint8_t len_be[8];
    for (int i = 0; i < 8; ++i)
      len_be[i] = static_cast<uint8_t>(bit_len >> (8 * (7 - i)));
    // Bypass update()'s total_ bookkeeping wouldn't matter now, but keep the
    // single code path: feed the length through update too.
    update(len_be, 8);
    static const char* digits = "0123456789abcdef";
    std::string out;
    out.reserve(64);
    for (uint32_t word : state_) {
      for (int shift = 28; shift >= 0; shift -= 4)
        out += digits[(word >> shift) & 0xF];
    }
    return out;
  }

 private:
  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void compress(const uint8_t* block) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (static_cast<uint32_t>(block[i * 4]) << 24) |
             (static_cast<uint32_t>(block[i * 4 + 1]) << 16) |
             (static_cast<uint32_t>(block[i * 4 + 2]) << 8) |
             static_cast<uint32_t>(block[i * 4 + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = state_[0], b = state_[1], c = state_[2], d = state_[3];
    uint32_t e = state_[4], f = state_[5], g = state_[6], h = state_[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + s1 + ch + k[i] + w[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      h = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    state_[0] += a; state_[1] += b; state_[2] += c; state_[3] += d;
    state_[4] += e; state_[5] += f; state_[6] += g; state_[7] += h;
  }

  uint32_t state_[8];
  uint64_t total_ = 0;
  uint8_t buf_[64];
  size_t buf_len_ = 0;
};

}  // namespace minisha
