// cgroup-v2 HARD enforcement for sandbox executions: memory.max / pids.max
// boxes around the warm-runner group and every cold subprocess, layered
// UNDER the existing rlimits + sampling watchdog (limits.hpp).
//
// Why a third layer: the rlimit window and the watchdog are cooperative-ish
// — rlimits can be dodged (native allocations, children raising their own
// soft limits) and the watchdog SAMPLES (default 100ms): an allocation
// burst faster than one tick, or a fork storm quicker than a /proc walk,
// can take the pod down before either fires. A cgroup's memory.max and
// pids.max are enforced by the KERNEL at the allocation/fork site — the
// in-pod limits story the quota layer (services/quotas.py) promises
// tenants actually holds even against watchdog-dodging workloads.
//
// Layering contract (deliberate): cgroup bounds carry HEADROOM above the
// watchdog's thresholds, so in the common case the watchdog still fires
// first with its clean typed report and baseline subtraction; the cgroup
// only acts when user code outruns it — and the post-run event counters
// (memory.events oom_kill, pids.events max) reclassify that generic death
// as the typed oom/nproc violation it actually was.
//
// Detection and fallback: enforcement arms only when the cgroup-v2
// hierarchy this process lives in is WRITABLE and delegates the memory and
// pids controllers (pods with a delegated cgroup namespace, root dev
// hosts). Anything else — v1/hybrid hosts, read-only cgroupfs, missing
// controllers, APP_CGROUP_ENFORCE=0 — degrades cleanly to today's
// rlimits+watchdog behavior, with the verdict (and the reason) surfaced on
// /healthz so the control plane and tests can see which mode a sandbox
// actually runs in.

#ifndef EXECUTOR_CGROUP_HPP_
#define EXECUTOR_CGROUP_HPP_

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>

namespace cgroup {

// One-shot whole-file write ("max", a limit, or a pid). False on any error.
inline bool write_file(const std::string& path, const std::string& data) {
  int fd = open(path.c_str(), O_WRONLY | O_TRUNC | O_CLOEXEC);
  if (fd < 0) return false;
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = write(fd, data.data() + off, data.size() - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      close(fd);
      return false;
    }
    off += static_cast<size_t>(n);
  }
  close(fd);
  return true;
}

inline std::string read_file(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (!f) return "";
  std::string out;
  char buf[512];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), f)) > 0) out.append(buf, n);
  fclose(f);
  return out;
}

// "<key> <value>" line from an events file (memory.events / pids.events);
// 0 when absent/unreadable — deltas then simply never classify.
inline long long read_event(const std::string& path, const char* key) {
  std::string body = read_file(path);
  size_t pos = 0;
  size_t keylen = strlen(key);
  while (pos < body.size()) {
    size_t eol = body.find('\n', pos);
    if (eol == std::string::npos) eol = body.size();
    if (body.compare(pos, keylen, key) == 0 && pos + keylen < eol &&
        body[pos + keylen] == ' ') {
      return atoll(body.c_str() + pos + keylen + 1);
    }
    pos = eol + 1;
  }
  return 0;
}

// The cgroup-v2 path THIS process lives in ("0::<path>" in /proc/self/cgroup),
// or "" on pure-v1 hosts. The base for delegation detection: in a pod (or a
// systemd-delegated scope) this is exactly the subtree the runtime handed us.
inline std::string self_v2_path() {
  std::string body = read_file("/proc/self/cgroup");
  size_t pos = 0;
  while (pos < body.size()) {
    size_t eol = body.find('\n', pos);
    if (eol == std::string::npos) eol = body.size();
    if (body.compare(pos, 3, "0::") == 0) {
      return body.substr(pos + 3, eol - pos - 3);
    }
    pos = eol + 1;
  }
  return "";
}

// Where the cgroup-v2 hierarchy is mounted: /sys/fs/cgroup on unified
// hosts, but hybrid hosts park it elsewhere (commonly
// /sys/fs/cgroup/unified) — the fstype in /proc/self/mounts is the truth.
inline std::string v2_mount_point() {
  std::string body = read_file("/proc/self/mounts");
  size_t pos = 0;
  while (pos < body.size()) {
    size_t eol = body.find('\n', pos);
    if (eol == std::string::npos) eol = body.size();
    std::string line = body.substr(pos, eol - pos);
    // "<dev> <mountpoint> <fstype> <opts> ..."
    size_t a = line.find(' ');
    size_t b = line.find(' ', a + 1);
    size_t c = line.find(' ', b + 1);
    if (a != std::string::npos && b != std::string::npos &&
        c != std::string::npos &&
        line.compare(b + 1, c - b - 1, "cgroup2") == 0) {
      return line.substr(a + 1, b - a - 1);
    }
    pos = eol + 1;
  }
  return "";
}

// Boot-time verdict: where per-run cgroups may be created, or why not.
struct Runtime {
  bool enabled = false;
  std::string base;    // the delegated dir new scopes are created under
  std::string reason;  // human-readable fallback reason when !enabled
};

// Detect + prepare the delegated subtree. Steps (any failure -> clean
// fallback with the step as the reason):
//  1. resolve the v2 dir this process lives in (APP_CGROUP_ROOT overrides —
//     for hosts where the operator delegated a different subtree);
//  2. require the memory and pids controllers in cgroup.controllers;
//  3. create a <base>/host leaf and move OUR process into it — cgroup v2's
//     no-internal-process rule forbids enabling controllers for children
//     while the parent still has member processes (in a pod the server is
//     the only one; on a shared host others remain and step 4 fails EBUSY,
//     which is the correct verdict: that subtree is not ours to partition);
//  4. enable "+memory +pids" in <base>/cgroup.subtree_control;
//  5. probe-create a scope and write memory.max/pids.max to prove the
//     delegation actually extends to the limit knobs.
inline Runtime init(bool enforce_enabled) {
  Runtime rt;
  if (!enforce_enabled) {
    rt.reason = "disabled by APP_CGROUP_ENFORCE=0";
    return rt;
  }
  const char* root_env = getenv("APP_CGROUP_ROOT");
  std::string base;
  if (root_env && *root_env) {
    base = root_env;
  } else {
    std::string path = self_v2_path();
    std::string mount = v2_mount_point();
    if (path.empty() || mount.empty()) {
      rt.reason = "no cgroup-v2 hierarchy (pure-v1 host)";
      return rt;
    }
    base = mount;
    if (path != "/") base += path;
  }
  while (base.size() > 1 && base.back() == '/') base.pop_back();
  std::string controllers = read_file(base + "/cgroup.controllers");
  if (controllers.empty()) {
    rt.reason = "no cgroup.controllers at " + base;
    return rt;
  }
  auto has = [&controllers](const char* name) {
    size_t pos = controllers.find(name);
    // token match: bounded by space/newline/start/end
    while (pos != std::string::npos) {
      size_t end = pos + strlen(name);
      bool left = pos == 0 || controllers[pos - 1] == ' ';
      bool right = end >= controllers.size() || controllers[end] == ' ' ||
                   controllers[end] == '\n';
      if (left && right) return true;
      pos = controllers.find(name, pos + 1);
    }
    return false;
  };
  if (!has("memory") || !has("pids")) {
    rt.reason = "memory/pids controllers not delegated at " + base;
    return rt;
  }
  std::string host = base + "/host";
  if (mkdir(host.c_str(), 0755) != 0 && errno != EEXIST) {
    rt.reason = "cgroupfs not writable at " + base;
    return rt;
  }
  char self_pid[32];
  snprintf(self_pid, sizeof(self_pid), "%d", getpid());
  if (!write_file(host + "/cgroup.procs", self_pid)) {
    rt.reason = "cannot move self into a leaf cgroup under " + base;
    return rt;
  }
  if (!write_file(base + "/cgroup.subtree_control", "+memory +pids")) {
    // Typically EBUSY: other processes share the subtree — it is not ours
    // to partition (shared dev host). The fallback is the correct answer.
    rt.reason = "cannot enable memory/pids for subtrees of " + base;
    return rt;
  }
  std::string probe = base + "/probe";
  if (mkdir(probe.c_str(), 0755) != 0 && errno != EEXIST) {
    rt.reason = "cannot create scopes under " + base;
    return rt;
  }
  bool ok = write_file(probe + "/memory.max", "max") &&
            write_file(probe + "/pids.max", "max");
  rmdir(probe.c_str());
  if (!ok) {
    rt.reason = "memory.max/pids.max not writable under " + base;
    return rt;
  }
  rt.enabled = true;
  rt.base = base;
  return rt;
}

// One enforcement scope: a child cgroup with memory.max/pids.max armed.
// Used two ways — a long-lived "runner" scope holding the warm runner group
// (bounded by the boot caps for the sandbox's whole life; refresh_baseline/
// violation bracket each request), and throwaway per-cold-run scopes
// (created armed, child self-attaches pre-exec, destroyed after).
class Scope {
 public:
  Scope() = default;

  static Scope create(const Runtime& rt, const std::string& name,
                      long long memory_max_bytes, long long pids_max) {
    Scope s;
    if (!rt.enabled) return s;
    std::string dir = rt.base + "/" + name;
    if (mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST) return s;
    char buf[32];
    bool ok = true;
    if (memory_max_bytes > 0) {
      snprintf(buf, sizeof(buf), "%lld", memory_max_bytes);
      ok = ok && write_file(dir + "/memory.max", buf);
      // Kill the whole group on OOM rather than letting the kernel pick
      // one victim: a half-dead runner group is the worst outcome (the
      // server would keep talking to a runner whose worker just vanished).
      write_file(dir + "/memory.oom.group", "1");  // best-effort (4.19+)
    }
    if (pids_max > 0) {
      snprintf(buf, sizeof(buf), "%lld", pids_max);
      ok = ok && write_file(dir + "/pids.max", buf);
    }
    if (!ok) {
      rmdir(dir.c_str());
      return s;
    }
    s.dir_ = dir;
    s.refresh_baseline();
    return s;
  }

  bool active() const { return !dir_.empty(); }
  const std::string& dir() const { return dir_; }
  // Membership is always SELF-attach: the forked child writes "0" to this
  // path before exec (race-free — every byte user code allocates is inside
  // the box). Deliberately no attach-by-pid helper: parent-side attachment
  // would race the fork it observes.
  std::string procs_path() const { return dir_ + "/cgroup.procs"; }

  // Re-read the event counters; call before a run so violation() reports
  // only what THAT run triggered (the runner scope is long-lived).
  void refresh_baseline() {
    if (!active()) return;
    oom_base_ = read_event(dir_ + "/memory.events", "oom_kill");
    pids_base_ = read_event(dir_ + "/pids.events", "max");
  }

  // Kernel-side enforcement evidence since the last baseline:
  // "oom" (memory.max OOM kills), "nproc" (fork/clone refused at pids.max),
  // or nullptr. Memory wins when both moved — an OOM kill is the stronger
  // (and rarer) signal.
  const char* violation() const {
    if (!active()) return nullptr;
    if (read_event(dir_ + "/memory.events", "oom_kill") > oom_base_)
      return "oom";
    if (read_event(dir_ + "/pids.events", "max") > pids_base_)
      return "nproc";
    return nullptr;
  }

  // Kill any members, then remove. cgroup.kill (5.14+) is best-effort; the
  // rmdir retries briefly while the kernel reaps. A scope that will not
  // die leaks one empty cgroup dir — logged by the caller, never fatal.
  bool destroy() {
    if (!active()) return true;
    write_file(dir_ + "/cgroup.kill", "1");
    for (int i = 0; i < 50; ++i) {
      if (rmdir(dir_.c_str()) == 0 || errno == ENOENT) {
        dir_.clear();
        return true;
      }
      usleep(10 * 1000);
    }
    return false;
  }

 private:
  std::string dir_;
  long long oom_base_ = 0;
  long long pids_base_ = 0;
};

}  // namespace cgroup

#endif  // EXECUTOR_CGROUP_HPP_
