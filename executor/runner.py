"""Warm execution runner: a persistent Python process that pre-initializes
JAX/TPU at sandbox boot and then executes user scripts on demand.

Why it exists (TPU design, SURVEY.md §7 hard part #2): libtpu init + device
enumeration costs seconds. The reference spawned a fresh interpreter per
execution (via xonsh, executor/server.rs:202-218), which is fine on CPU but
would put TPU init on every Execute's critical path. Here the executor server
(server.cpp) starts this runner when the sandbox boots — i.e. while the
sandbox is still sitting in the warm pool — so by the time an Execute arrives,
`import jax` and device init are already done and user code sees a hot TPU.

Protocol: newline-delimited JSON. fd 3 = requests in, fd 4 = responses out.
Request:  {"source_path": ..., "stdout_path": ..., "stderr_path": ..., "env": {...}}
Response: {"exit_code": int}
Ready line (sent once at boot):
  {"ready": true, "backend": ..., "device_count": n, "device_kind": ...}

User scripts run in-process via runpy with stdout/stderr redirected at the fd
level, fresh sys.argv, and __main__ semantics.

Sandboxes are single-use, but the runner is NOT: the TPU lease (this process,
with jax imported and the chip attached) outlives each sandbox generation.
Between generations the server sends a `{"op": "reset"}` request and the
runner scrubs every trace of the previous user: stray child processes are
killed, workspace-origin modules are dropped from sys.modules, os.environ and
cwd and sys.stdout/stderr are restored to their boot snapshot, and device
buffers are garbage-collected. Only after an ok-reset does the control plane
hand the sandbox to a new request; anything un-scrubbable (runner killed on
timeout, reset failure) falls back to full process disposal. This is what
keeps Execute p50 at pool-pop speed instead of a ~seconds jax/libtpu re-init
per request (the round-2 bench's 3.4 s queue_wait).
"""

import json
import os
import runpy
import sys
import threading
import time
import traceback

REQ_FD = 3
RESP_FD = 4

# Trace context for runner-authored log lines: the server forwards the
# request's trace id (parsed from the control plane's traceparent) and the
# runner prefixes its own diagnostics with it, so a demuxed batch job's
# sandbox output is attributable to its originating request. Thread-local:
# batch jobs run in threads, each under its own request's trace id.
_TRACE_LOCAL = threading.local()


def _set_trace_id(trace_id) -> None:
    _TRACE_LOCAL.trace_id = trace_id if isinstance(trace_id, str) else None


def _log(msg: str) -> None:
    """Runner diagnostic line, trace-id-prefixed when the request carried
    trace context (goes to the executor's log via inherited stderr, or to
    the job's capture while a redirect is active — both are the places an
    operator reconstructs a batched run from)."""
    trace_id = getattr(_TRACE_LOCAL, "trace_id", None)
    prefix = f"[runner trace={trace_id}] " if trace_id else "[runner] "
    try:
        sys.stderr.write(prefix + msg + "\n")
    except Exception:  # noqa: BLE001 — logging must never kill the runner
        pass

# Persistent-compilation-cache traffic, counted via jax.monitoring events
# (registered in _warm_import, best-effort): the per-request delta rides the
# execute reply so the fleet compile cache's hit rate is observable per run.
_CACHE_EVENTS = {"hits": 0, "requests": 0, "misses": 0}
_CACHE_LISTENING = False


def _register_cache_listener() -> None:
    """Count compilation-cache hit/miss monitoring events. jax's public
    surface for this moved across versions, so resolve defensively — a miss
    just means hit/miss counts stay unreported (the server's cache-dir diff
    still reports new entries)."""
    global _CACHE_LISTENING
    try:
        from jax._src import monitoring
    except ImportError:
        return

    def on_event(event: str, *args, **kwargs) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            _CACHE_EVENTS["hits"] += 1
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            _CACHE_EVENTS["requests"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            _CACHE_EVENTS["misses"] += 1

    try:
        monitoring.register_event_listener(on_event)
        _CACHE_LISTENING = True
    except Exception:  # noqa: BLE001 — observability must not break warm-up
        traceback.print_exc()


def _cache_counts() -> tuple[int, int]:
    """(hits, misses) so far. Misses prefer the explicit event; older jax
    only emits requests+hits, where misses = requests - hits."""
    hits = _CACHE_EVENTS["hits"]
    misses = _CACHE_EVENTS["misses"] or max(
        0, _CACHE_EVENTS["requests"] - hits
    )
    return hits, misses


def _send(obj: dict) -> None:
    try:
        os.write(RESP_FD, (json.dumps(obj) + "\n").encode())
    except OSError:
        # Server died while we were executing; nothing left to report to.
        # Skip atexit (jax.distributed shutdown would block on dead peers).
        os._exit(0)


def _distributed_init(jax) -> None:
    """Multi-host slice bootstrap (SURVEY.md §7.6): the backend spawns one
    executor per host with APP_NUM_HOSTS / APP_HOST_ID / APP_COORDINATOR_ADDR;
    host 0 binds the coordinator, peers dial it over DCN, and after this call
    every host sees the slice's full device set — user code gets a
    pre-established global mesh without any cooperation on its part (the
    reference's NCCL/MPI role, done the JAX way)."""
    num_hosts = int(os.environ.get("APP_NUM_HOSTS", "1") or "1")
    if num_hosts <= 1:
        return
    coordinator = os.environ["APP_COORDINATOR_ADDR"]
    host_id = int(os.environ.get("APP_HOST_ID", "0"))
    # On the CPU platform (tests, dev) cross-process collectives need gloo;
    # the knob is ignored by the TPU backend, which uses ICI.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — older jaxlib without the knob
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=host_id,
    )


def _warm_import() -> dict:
    """Pre-import jax and touch the devices so TPU init happens now."""
    info = {"ready": True, "backend": "none", "device_count": 0}
    num_hosts = int(os.environ.get("APP_NUM_HOSTS", "1") or "1")
    if os.environ.get("APP_WARM_IMPORT_JAX", "1") in ("0", "false"):
        # Explicit escape hatch (plumbing tests / no-JAX dev); on a slice
        # this forgoes the mesh knowingly.
        return info
    try:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
        import jax

        if cache_dir:
            _register_cache_listener()

        _distributed_init(jax)
        if cache_dir:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # Persist every kernel: the default 1s min-compile-time filter
            # would skip most eager-op kernels, so fresh sandboxes would
            # recompile everything and the pool's cache amortization
            # (SURVEY.md §7 hard part #2) would never engage.
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        devices = jax.devices()
        info["backend"] = devices[0].platform if devices else "none"
        info["device_count"] = len(devices)  # global across the slice
        # Device kind for the telemetry plane ("TPU v5e" etc.; CPU devices
        # report "cpu") — surfaced via GET /device-stats so operators see
        # what hardware a lane's hosts actually hold.
        info["device_kind"] = (
            str(getattr(devices[0], "device_kind", "")) if devices else ""
        )
        if jax.process_count() > 1:
            info["process_count"] = jax.process_count()
            info["process_index"] = jax.process_index()
            info["local_device_count"] = jax.local_device_count()
        # Trigger one tiny compile so the XLA pipeline is paged in.
        import jax.numpy as jnp

        jnp.add(jnp.ones(()), 1.0).block_until_ready()
    except Exception:  # noqa: BLE001 — sandbox must still run CPU-only code
        traceback.print_exc()
        if num_hosts > 1:
            # A host that failed jax/distributed init must NOT report ready:
            # the pod would pass its probe and hand out a slice whose mesh
            # silently doesn't exist. Exiting keeps the server from ever
            # listening (server.cpp refuses multi-host without the runner).
            _log("fatal: jax init failed on a multi-host slice")
            os._exit(1)
        info["backend"] = "import-failed"
    return info


def _profile_requested(env: dict) -> bool:
    return str(env.get("APP_JAX_PROFILE", "")).lower() not in ("", "0", "false")


def _device_memory_snapshot() -> tuple[int, int]:
    """(live_bytes, peak_bytes) summed across local devices, or -1 where
    the signal is unavailable. TPU/GPU devices report allocator stats via
    device.memory_stats() (bytes_in_use / peak_bytes_in_use); the CPU
    platform usually reports none, so live bytes fall back to summing
    jax.live_arrays() (no peak tracking there — the caller brackets the
    run and uses max(before, after) instead). Never imports jax: if the
    warm import didn't run, there is nothing to measure."""
    jax = sys.modules.get("jax")
    if jax is None:
        return -1, -1
    try:
        live = peak = 0
        reported = False
        for device in jax.local_devices():
            stats_fn = getattr(device, "memory_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if not isinstance(stats, dict):
                continue
            in_use = stats.get("bytes_in_use")
            if not isinstance(in_use, int):
                continue
            reported = True
            live += in_use
            peak_b = stats.get("peak_bytes_in_use")
            peak += peak_b if isinstance(peak_b, int) else in_use
        if reported:
            return live, peak
        total = 0
        for arr in jax.live_arrays():
            nbytes = getattr(arr, "nbytes", 0)
            if isinstance(nbytes, int):
                total += nbytes
        return total, -1
    except Exception:  # noqa: BLE001 — accounting must never kill a run
        return -1, -1


def _rss_bytes() -> int:
    """This process's resident set, or -1."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return -1


class _DeviceMemoryProbe:
    """Brackets one run with device-memory samples and shapes the reply
    block. Armed per request (the control plane asks via the request's
    `device_memory` flag — the perf-observer kill switch keeps sampling,
    and its tiny cost, entirely off the wire when the plane is off)."""

    __slots__ = ("live_before", "peak_before")

    def __init__(self) -> None:
        self.live_before, self.peak_before = _device_memory_snapshot()

    def finish(self) -> dict:
        live_after, peak_after = _device_memory_snapshot()
        return {
            "live_bytes_before": self.live_before,
            "live_bytes_after": live_after,
            "peak_bytes_before": self.peak_before,
            "peak_bytes_after": peak_after,
            "rss_bytes": _rss_bytes(),
        }


def _resolve_mem_budget() -> int:
    """APP_MAX_USER_MEMORY_BYTES: extra address-space bytes user code may
    allocate beyond the warm baseline. "auto" = 80% of the host's physical
    RAM; 0/unset = no limit."""
    raw = os.environ.get("APP_MAX_USER_MEMORY_BYTES", "").strip().lower()
    if not raw or raw in ("0", "false", "off"):
        return 0
    if raw == "auto":
        try:
            return int(
                0.8 * os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
            )
        except (ValueError, OSError):
            return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return 0


class _CpuTimeExceeded(BaseException):
    """Raised by the SIGXCPU handler when the per-request CPU budget runs
    out: a BaseException so user-code `except Exception` blocks can't
    swallow the limit, unwinding to _run_one which reports the typed
    `cpu_time` violation — the warm process (and its device lease) stays
    alive, unlike the executor watchdog's group kill."""


def _request_limit(limits: dict, key: str, env_value: int) -> int:
    """Effective in-process bound: request value min-clamped by the env
    budget (operator policy may only be tightened, never raised)."""
    try:
        requested = int(limits.get(key) or 0)
    except (TypeError, ValueError):
        requested = 0
    if requested <= 0:
        return env_value
    if env_value <= 0:
        return requested
    return min(requested, env_value)


def _apply_user_rlimits(limits: dict | None = None):
    """Bound the user script with soft rlimits; returns a restore thunk.

    RLIMIT_AS soft = current VmSize + budget: an allocation bomb inside
    user code gets a clean in-process MemoryError (traceback in its stderr,
    exit_code 1) instead of inviting the host OOM killer. The window is
    relative to the CURRENT footprint because the warm runner already holds
    jax + device mappings — an absolute cap below that would fail every
    future mmap including benign ones. RLIMIT_NOFILE soft comes from
    APP_MAX_OPEN_FILES (0 = inherit).

    `limits` is the per-request budget the executor server forwards
    (memory_bytes / cpu_seconds / nofile / fsize_bytes) — request values
    only ever TIGHTEN the env policy. cpu_seconds arms a soft RLIMIT_CPU at
    (current process CPU + budget) with a SIGXCPU handler that raises
    _CpuTimeExceeded, and fsize_bytes arms a soft RLIMIT_FSIZE with SIGXFSZ
    ignored so an oversized write surfaces as OSError(EFBIG) instead of the
    default signal killing the warm process.

    Soft-only on purpose: the hard limits stay put so the post-run restore
    works without privilege. This is a guardrail against runaway agent
    snippets, not a security boundary (user code could raise its own soft
    limit — the executor's watchdog is the backstop; same residual-risk
    contract as _reset's). The kubernetes backend bounds memory with
    container resources instead; the reference delegates isolation
    wholesale to the cluster runtime (README.md:56-57).
    """
    import resource
    import signal as _signal

    limits = limits or {}
    restores = []
    signal_restores = []

    def lower_soft(which, target) -> None:
        soft, hard = resource.getrlimit(which)
        if hard != resource.RLIM_INFINITY:
            target = min(target, hard)
        if soft == resource.RLIM_INFINITY or target < soft:
            resource.setrlimit(which, (target, hard))
            restores.append((which, (soft, hard)))

    budget = _request_limit(limits, "memory_bytes", _resolve_mem_budget())
    if budget > 0:
        try:
            with open("/proc/self/statm") as f:
                vm_bytes = int(f.read().split()[0]) * os.sysconf("SC_PAGE_SIZE")
            lower_soft(resource.RLIMIT_AS, vm_bytes + budget)
        except (OSError, ValueError):
            pass
    nofile_raw = os.environ.get("APP_MAX_OPEN_FILES", "").strip()
    nofile_env = int(nofile_raw) if nofile_raw.isdigit() else 0
    nofile = _request_limit(limits, "nofile", nofile_env)
    if nofile > 0:
        try:
            lower_soft(resource.RLIMIT_NOFILE, nofile)
        except (OSError, ValueError):
            pass
    fsize = _request_limit(limits, "fsize_bytes", 0)
    if fsize > 0:
        try:
            lower_soft(resource.RLIMIT_FSIZE, fsize)
            saved = _signal.signal(_signal.SIGXFSZ, _signal.SIG_IGN)
            signal_restores.append((_signal.SIGXFSZ, saved))
        except (OSError, ValueError):
            pass
    try:
        cpu_budget = float(limits.get("cpu_seconds") or 0)
    except (TypeError, ValueError):
        cpu_budget = 0.0
    if cpu_budget > 0:
        try:
            usage = resource.getrusage(resource.RUSAGE_SELF)
            spent = usage.ru_utime + usage.ru_stime

            def on_xcpu(signum, frame):
                raise _CpuTimeExceeded(
                    f"CPU time limit ({cpu_budget:.0f}s) exceeded"
                )

            saved = _signal.signal(_signal.SIGXCPU, on_xcpu)
            signal_restores.append((_signal.SIGXCPU, saved))
            # RLIMIT_CPU has whole-second granularity and counts the whole
            # process, so the soft ceiling rides on top of what the warm
            # runner has already spent.
            lower_soft(resource.RLIMIT_CPU, int(spent + cpu_budget) + 1)
        except (OSError, ValueError):
            pass

    def restore() -> None:
        # Idempotent (pops as it goes): called from the except path to get
        # headroom back BEFORE traceback formatting, then again in finally.
        while restores:
            lim, vals = restores.pop()
            try:
                resource.setrlimit(lim, vals)
            except (OSError, ValueError):
                pass
        while signal_restores:
            signum, handler = signal_restores.pop()
            try:
                _signal.signal(signum, handler)
            except (ValueError, TypeError, OSError):
                pass

    return restore


def _import_jax_profile():
    return _import_sibling("jax_profile")


def _start_profile() -> str | None:
    """Begin a JAX profiler trace; returns the trace dir, or None."""
    try:
        return _import_jax_profile().start_trace()
    except Exception:  # noqa: BLE001 — profiling is best-effort
        traceback.print_exc()
        return None


def _finish_profile(trace_dir: str) -> None:
    """Stop the trace and zip it to ./profile.zip (cwd = workspace, so the
    changed-file scan returns it to the client)."""
    try:
        _import_jax_profile().finish_trace(trace_dir)
    except Exception:  # noqa: BLE001
        traceback.print_exc()


def _import_sibling(name: str):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# APP_JAX_PROFILE stays out of os.environ: the warm runner profiles the
# run itself, and leaking the var would make a sitecustomize on the path
# double-start the profiler at first jax import. The rlimit knobs stay
# out too: they are operator policy from the sandbox's boot env, and a
# request-supplied override would let the very snippets the guardrail
# targets turn it off.
_OPERATOR_ONLY = (
    "APP_JAX_PROFILE",
    "APP_MAX_USER_MEMORY_BYTES",
    "APP_MAX_OPEN_FILES",
)


def _run_one(req: dict) -> tuple[int, str | None]:
    """Execute one request; returns (exit_code, violation) where violation
    is the typed limit kind when an in-process resource guard ended the run
    (None otherwise — including plain user errors)."""
    source_path = req["source_path"]
    run_path = source_path
    try:
        # Mixed Python/shell snippets run via the shellfb transform — the
        # xonsh role (reference server.rs:197-207) without its 80 ms tax.
        run_path = _import_sibling("shellfb").prepare(source_path)
    except Exception:  # noqa: BLE001 — fallback is best-effort
        traceback.print_exc()
    env = req.get("env") or {}
    env_to_set = {k: v for k, v in env.items() if k not in _OPERATOR_ONLY}
    saved_env = {k: os.environ.get(k) for k in env_to_set}
    os.environ.update({k: str(v) for k, v in env_to_set.items()})

    out_fd = os.open(req["stdout_path"], os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    err_fd = os.open(req["stderr_path"], os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    saved_out, saved_err = os.dup(1), os.dup(2)
    sys.stdout.flush()
    sys.stderr.flush()
    os.dup2(out_fd, 1)
    os.dup2(err_fd, 2)
    os.close(out_fd)
    os.close(err_fd)
    saved_argv = sys.argv
    exit_code = 0
    violation = None
    limits = req.get("limits") or {}
    # Is a memory budget actually armed? A MemoryError under an armed window
    # is the oom violation caught cleanly; without one it is ordinary user
    # code raising (or exhausting the host for real — the watchdog's case).
    mem_limited = _request_limit(limits, "memory_bytes", _resolve_mem_budget()) > 0
    trace_dir = _start_profile() if _profile_requested(env) else None
    restore_rlimits = _apply_user_rlimits(limits)
    # User code may rebind/ignore SIGINT; restore it afterwards or a single
    # tenant could permanently disable the server's cooperative timeout
    # cancellation for every later generation of this warm process.
    import signal as _signal

    saved_sigint = _signal.getsignal(_signal.SIGINT)
    try:
        sys.argv = [source_path]  # argv[0] stays the user's path
        runpy.run_path(run_path, run_name="__main__")
    except SystemExit as e:
        code = e.code
        exit_code = code if isinstance(code, int) else (0 if code is None else 1)
    except _CpuTimeExceeded:
        # Restore first: the soft RLIMIT_CPU re-fires SIGXCPU every second
        # past the ceiling, and the next one must not land mid-report.
        restore_rlimits()
        traceback.print_exc()
        exit_code = 1
        violation = "cpu_time"
    except MemoryError:
        # Limits off first: after a window-exhausting MemoryError, the
        # traceback formatting itself needs allocation headroom.
        restore_rlimits()
        traceback.print_exc()
        exit_code = 1
        if mem_limited:
            violation = "oom"
    except BaseException:  # noqa: BLE001 — report, don't die
        restore_rlimits()
        traceback.print_exc()
        exit_code = 1
    finally:
        restore_rlimits()
        try:
            _signal.signal(_signal.SIGINT, saved_sigint)
        except (ValueError, TypeError):  # non-main thread / exotic handler
            pass
        sys.argv = saved_argv
        if trace_dir is not None:
            # Inside the redirect so profiler chatter lands in the capture.
            _finish_profile(trace_dir)
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001
            pass
        os.dup2(saved_out, 1)
        os.dup2(saved_err, 2)
        os.close(saved_out)
        os.close(saved_err)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if run_path != source_path:
            try:
                os.unlink(run_path)
            except OSError:
                pass
    return exit_code, violation


# ---------------------------------------------------------------------------
# Batched dispatch (the "op": "batch" request): N small jobs from ONE tenant
# run concurrently in this warm process, each thread pinned to its own
# device of the lane's local device set — the Anakin/Sebulba placement that
# keeps every chip of a multi-chip slice busy instead of idling 7/8 of it
# behind serial round-trips. One address space means env, rlimits, and the
# CPU budget are BATCH-level (the control plane only coalesces jobs whose
# env and limits are identical); stdout/stderr are demuxed per job via a
# thread-routing stream proxy, and each job thread gets a PRIVATE cwd via
# unshare(CLONE_FS) so relative-path file writes land in its own workdir.


class _StreamRouter:
    """sys.stdout/sys.stderr stand-in during a batched run: writes route to
    the calling thread's bound per-job capture file, falling back to the
    batch-level stream for main-thread/runner output. fd-level writes from
    C extensions bypass Python streams and land in the batch-level capture
    — the server surfaces batch-level stdout and the control plane then
    reruns the batch serially, so that output is never dropped."""

    def __init__(self, fallback) -> None:
        self._fallback = fallback
        self._local = threading.local()

    def bind(self, fh) -> None:
        self._local.fh = fh

    def unbind(self) -> None:
        self._local.fh = None

    @property
    def _target(self):
        return getattr(self._local, "fh", None) or self._fallback

    def write(self, data) -> int:
        return self._target.write(data)

    def writelines(self, lines) -> None:
        self._target.writelines(lines)

    def flush(self) -> None:
        try:
            self._target.flush()
        except ValueError:  # closed underlying file
            pass

    def isatty(self) -> bool:
        return False

    @property
    def encoding(self):
        return getattr(self._target, "encoding", "utf-8")

    def fileno(self) -> int:
        return self._fallback.fileno()


_CLONE_FS = 0x00000200


def _unshare_fs() -> bool:
    """Give the calling THREAD a private filesystem context (cwd/umask) via
    unshare(CLONE_FS), so concurrent batch jobs each chdir into their own
    workdir without racing. No privilege needed. False when unavailable
    (non-Linux libc, seccomp policy) — the job then runs from the shared
    workspace root and its relative-path writes are not demuxable."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        return libc.unshare(_CLONE_FS) == 0
    except Exception:  # noqa: BLE001
        return False


def _job_device_ctx(device_index, fallback_index: int):
    """Pin the job thread's jax dispatches to one local device (the batch's
    device-axis placement). jax config context managers are thread-local,
    so concurrent jobs land on distinct chips. No jax / no devices / old
    jax without default_device → a null context (CPU-only jobs run fine)."""
    import contextlib

    try:
        jax = sys.modules.get("jax")
        if jax is not None and hasattr(jax, "default_device"):
            devices = jax.devices()
            if devices:
                index = (
                    device_index
                    if isinstance(device_index, int)
                    else fallback_index
                )
                return jax.default_device(devices[index % len(devices)])
    except Exception:  # noqa: BLE001 — placement is best-effort
        pass
    return contextlib.nullcontext()


def _run_batch_job(index: int, job: dict, results: list, mem_limited: bool,
                   proxies: tuple, t_base: float,
                   want_memory: bool = False) -> None:
    """One job thread: bind capture files, isolate cwd, pin the device,
    exec the source. Never raises — the entry records the outcome (a
    per-job MemoryError under an armed budget is THIS job's typed oom
    violation; its batchmates never notice)."""
    proxy_out, proxy_err = proxies
    _set_trace_id(job.get("trace_id"))
    start = time.monotonic()
    entry = {
        "exit_code": 0,
        "start_offset_s": round(max(0.0, start - t_base), 6),
    }
    # Per-job device-memory bracket. One address space means concurrent
    # batchmates' allocations land inside each other's windows — the
    # per-job delta is best-effort under concurrency (documented on the
    # wire block); the batch-level peak stays exact.
    mem_probe = _DeviceMemoryProbe() if want_memory else None
    out = err = None
    try:
        out = open(job["stdout_path"], "w", buffering=1)
        err = open(job["stderr_path"], "w", buffering=1)
        proxy_out.bind(out)
        proxy_err.bind(err)
        isolated = _unshare_fs()
        if isolated:
            try:
                os.chdir(job["cwd"])
            except OSError:
                isolated = False
        entry["cwd_isolated"] = isolated
        if not isolated:
            _log(
                "batch job %d: no per-thread cwd isolation; relative-path "
                "writes land in the shared workspace" % index
            )
        source_path = job["source_path"]
        with open(source_path) as f:
            code = compile(f.read(), source_path, "exec")
        with _job_device_ctx(job.get("device_index"), index):
            exec(  # noqa: S102 — this IS the sandbox's purpose
                code,
                {
                    "__name__": "__main__",
                    "__file__": source_path,
                    "__builtins__": __builtins__,
                },
            )
    except SystemExit as e:
        code_ = e.code
        entry["exit_code"] = (
            code_ if isinstance(code_, int) else (0 if code_ is None else 1)
        )
    except MemoryError:
        traceback.print_exc()  # routed to this job's stderr by the proxy
        entry["exit_code"] = 1
        if mem_limited:
            entry["violation"] = "oom"
    except BaseException:  # noqa: BLE001 — report, don't die
        traceback.print_exc()
        entry["exit_code"] = 1
    finally:
        entry["duration_s"] = round(time.monotonic() - start, 6)
        if mem_probe is not None:
            entry["device_memory"] = mem_probe.finish()
        proxy_out.unbind()
        proxy_err.unbind()
        for fh in (out, err):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        _set_trace_id(None)
        results[index] = entry


def _run_batch(req: dict) -> dict:
    """Execute a coalesced batch: all jobs concurrently, one reply carrying
    per-job results. Batch-level state (env, rlimits, SIGINT handler, the
    fd-level redirect) is set up once around the whole run — the control
    plane only batches jobs whose env/limits are identical, so there is
    nothing per-job to disagree about."""
    jobs = req.get("jobs") or []
    if not jobs:
        return {"exit_code": -2, "error": "empty batch"}
    env = req.get("env") or {}
    env_to_set = {k: v for k, v in env.items() if k not in _OPERATOR_ONLY}
    saved_env = {k: os.environ.get(k) for k in env_to_set}
    os.environ.update({k: str(v) for k, v in env_to_set.items()})
    limits = req.get("limits") or {}
    mem_limited = (
        _request_limit(limits, "memory_bytes", _resolve_mem_budget()) > 0
    )
    # fd-level redirect to the batch capture (C-extension writes); Python-
    # level streams route per job through the proxies.
    out_fd = os.open(
        req["stdout_path"], os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644
    )
    err_fd = os.open(
        req["stderr_path"], os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644
    )
    saved_out, saved_err = os.dup(1), os.dup(2)
    sys.stdout.flush()
    sys.stderr.flush()
    os.dup2(out_fd, 1)
    os.dup2(err_fd, 2)
    os.close(out_fd)
    os.close(err_fd)
    fallback_out = os.fdopen(os.dup(1), "w", buffering=1)
    fallback_err = os.fdopen(os.dup(2), "w", buffering=1)
    proxy_out = _StreamRouter(fallback_out)
    proxy_err = _StreamRouter(fallback_err)
    prev_stdout, prev_stderr = sys.stdout, sys.stderr
    sys.stdout, sys.stderr = proxy_out, proxy_err
    restore_rlimits = _apply_user_rlimits(limits)
    import signal as _signal

    saved_sigint = _signal.getsignal(_signal.SIGINT)
    results: list = [None] * len(jobs)
    violation = None
    t_base = time.monotonic()
    want_memory = bool(req.get("device_memory"))
    threads = [
        threading.Thread(
            target=_run_batch_job,
            args=(i, job, results, mem_limited, (proxy_out, proxy_err), t_base,
                  want_memory),
            name=f"batch-job-{i}",
            daemon=True,
        )
        for i, job in enumerate(jobs)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    except _CpuTimeExceeded:
        # The batch's shared CPU budget ran out (the rlimit counts the
        # whole process — signal lands here, in the joining main thread,
        # unattributable to one job). Restore limits FIRST: the soft
        # ceiling re-fires every second past it.
        restore_rlimits()
        violation = "cpu_time"
    except MemoryError:
        restore_rlimits()
        if mem_limited:
            violation = "oom"
    except BaseException:  # noqa: BLE001 — report, don't die
        restore_rlimits()
        traceback.print_exc()
    finally:
        restore_rlimits()
        try:
            _signal.signal(_signal.SIGINT, saved_sigint)
        except (ValueError, TypeError):
            pass
        sys.stdout, sys.stderr = prev_stdout, prev_stderr
        for fh in (fallback_out, fallback_err):
            try:
                fh.close()
            except OSError:
                pass
        os.dup2(saved_out, 1)
        os.dup2(saved_err, 2)
        os.close(saved_out)
        os.close(saved_err)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    aborted = violation is not None
    for i, entry in enumerate(results):
        if entry is None:
            # Thread never finished (batch-level abort while it ran): its
            # result is unusable — the control plane re-runs it serially.
            results[i] = {"exit_code": -1, "aborted": True}
    reply = {"jobs": results, "exit_code": 0}
    if violation:
        reply["violation"] = violation
    if aborted:
        reply["batch_aborted"] = True
    return reply


def _descendant_pids() -> list[int]:
    """All live descendants of this process, via one /proc scan (user code
    runs in-process, so anything it spawned is a child of the runner)."""
    children: dict[int, list[int]] = {}
    try:
        entries = os.listdir("/proc")
    except OSError:
        return []
    for entry in entries:
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat", "rb") as f:
                stat = f.read()
            # Fields after the parenthesized comm: state, ppid, ...
            ppid = int(stat.rsplit(b") ", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            continue
        children.setdefault(ppid, []).append(int(entry))
    victims: list[int] = []
    stack = [os.getpid()]
    while stack:
        for child in children.get(stack.pop(), []):
            victims.append(child)
            stack.append(child)
    return victims


def _reset(snapshot: dict) -> bool:
    """Scrub per-generation state so the warm process can serve a fresh
    sandbox: the device lease survives, the previous user's traces do not.

    Returns False when the process is NOT scrubbable — the control plane
    must dispose it instead of recycling. Unscrubbable today: user code left
    a live thread behind (it would keep running beside the next
    generation's code; threads cannot be killed from outside in CPython).

    The full gc (which releases the previous user's host+device buffers) is
    the caller's job AFTER acking the reset: in a jax-laden interpreter a
    full collection costs tens of ms, and running it post-ack lets it
    overlap the control plane's workspace wipe and pool bookkeeping instead
    of sitting on the next request's queue-wait.

    Residual-risk contract (documented, not silently assumed): in-place
    mutations of SHARED module state (e.g. ``json.loads = evil``) by hostile
    code are not detectable and not scrubbed — process reuse trades that
    sliver of isolation for the TPU lease surviving generations. Deployments
    executing mutually-hostile tenants should set
    APP_EXECUTOR_REUSE_SANDBOXES=0 and pay the respawn (the reference's
    single-use-pod model)."""
    import signal
    import threading
    import time

    victims = _descendant_pids()
    for pid in victims:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    # Reap, not just kill: a zombie still "exists" to the next generation's
    # process checks. Direct children are waited for (bounded — SIGKILL is
    # prompt outside unkillable D-state); deeper descendants get reparented
    # and reaped by init once their parent dies.
    deadline = time.time() + 5.0
    for pid in victims:
        while True:
            try:
                done, _ = os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                break  # not our direct child (or already reaped)
            if done == pid or time.time() > deadline:
                break
            time.sleep(0.01)
    # A thread the previous generation started would keep running beside —
    # and observing — the next generation's code; CPython cannot kill it.
    # Compare against the boot snapshot (jax may own internal Python
    # threads) and refuse the reset if anything new is still alive.
    survivors = [
        t
        for t in threading.enumerate()
        if t.is_alive() and t.ident not in snapshot["threads"]
    ]
    if survivors:
        _log(
            "reset refused: user thread(s) survived: "
            f"{[t.name for t in survivors]}"
        )
        return False
    # A module imported from the previous generation's workspace, exec
    # scratch, or auto-installed runtime-packages must not shadow the next
    # generation's — the server wipes runtime-packages on disk, so a stale
    # sys.modules entry would resurrect a package the wipe just removed.
    import tempfile

    workspace = snapshot["cwd"]
    # Exec scratch dirs live under TMPDIR (sandbox-private when the backend
    # provides one) — match wherever they actually are.
    prefixes = [workspace + os.sep, os.path.join(tempfile.gettempdir(), "exec-")]
    runtime_packages = os.environ.get("APP_RUNTIME_PACKAGES")
    if runtime_packages:
        prefixes.append(runtime_packages.rstrip(os.sep) + os.sep)
    for name, mod in list(sys.modules.items()):
        origin = getattr(mod, "__file__", None) or ""
        if any(origin.startswith(p) for p in prefixes):
            del sys.modules[name]
    os.environ.clear()
    os.environ.update(snapshot["environ"])
    try:
        os.chdir(workspace)
    except OSError:
        pass
    # User code may have rebound the stream objects (fd redirection in
    # _run_one restores fds, not Python-level bindings).
    sys.stdout, sys.stderr = sys.__stdout__, sys.__stderr__
    sys.path[:] = snapshot["path"]
    return True


# Interpreter-state serialization (session durability): the cross-turn
# state this runner actually carries. Per-turn globals do NOT persist
# (each turn runs under runpy with a fresh namespace), so what survives —
# and what a snapshot must capture — is exactly: env-var mutations made by
# user code, the working directory, and workspace-origin modules whose
# module-level globals user turns import and mutate. Device buffers are
# deliberately NOT captured: they re-materialize on first touch after a
# restore (recompute/reload is the contract, same as a process restart).
_STATE_VERSION = 1

# Values are pickled by ALLOWLIST, not by "whatever pickles": only plain
# data (scalars + containers thereof) rides a snapshot. Anything else —
# open files, sockets, threads, jax arrays, live objects of workspace
# classes — is skipped and honestly reported, never half-captured.
_PICKLE_SCALARS = (type(None), bool, int, float, complex, str, bytes)


def _plain_data(value: object, depth: int = 0) -> bool:
    if depth > 8:
        return False
    if isinstance(value, _PICKLE_SCALARS):
        return True
    if isinstance(value, (list, tuple, set, frozenset)):
        return all(_plain_data(v, depth + 1) for v in value)
    if isinstance(value, dict):
        return all(
            _plain_data(k, depth + 1) and _plain_data(v, depth + 1)
            for k, v in value.items()
        )
    return False


class _PlainUnpickler:
    """Restricted loads(): refuses any global lookup, so a corrupted or
    adversarial snapshot blob cannot instantiate arbitrary classes — plain
    data needs no globals at all."""

    def __init__(self) -> None:
        import io
        import pickle

        class Unpickler(pickle.Unpickler):
            def find_class(self, module, name):  # noqa: ARG002
                raise pickle.UnpicklingError(
                    f"snapshot state may not reference {module}.{name}"
                )

        self._io = io
        self._cls = Unpickler

    def loads(self, data: bytes) -> object:
        return self._cls(self._io.BytesIO(data)).load()


def _workspace_module_prefixes(snapshot: dict) -> list[str]:
    """Same selection rule _reset uses to scrub: a module is session state
    (not interpreter infrastructure) iff its file lives under the
    workspace, exec scratch, or auto-installed runtime-packages."""
    import tempfile

    workspace = snapshot["cwd"]
    prefixes = [workspace + os.sep, os.path.join(tempfile.gettempdir(), "exec-")]
    runtime_packages = os.environ.get("APP_RUNTIME_PACKAGES")
    if runtime_packages:
        prefixes.append(runtime_packages.rstrip(os.sep) + os.sep)
    return prefixes


def _installed_packages() -> list[str]:
    """Top-level names under the auto-install dir — recorded in the
    snapshot for honesty/observability (restore does NOT reinstall; the
    package FILES ride the workspace manifest like any other files)."""
    runtime_packages = os.environ.get("APP_RUNTIME_PACKAGES")
    if not runtime_packages:
        return []
    try:
        return sorted(os.listdir(runtime_packages))
    except OSError:
        return []


def _snapshot_state(snapshot: dict, req: dict) -> dict:
    """Serialize this runner's cross-turn interpreter state into a JSON
    document (op "snapshot"). Never raises on a weird value — skipped
    names are reported, the rest is captured."""
    import base64
    import pickle

    boot_env = snapshot["environ"]
    env_set = {
        k: v
        for k, v in os.environ.items()
        if boot_env.get(k) != v
    }
    env_del = sorted(k for k in boot_env if k not in os.environ)
    try:
        cwd = os.getcwd()
    except OSError:
        cwd = snapshot["cwd"]

    prefixes = _workspace_module_prefixes(snapshot)
    modules = []
    skipped: list[str] = []
    for name, mod in sorted(sys.modules.items()):
        origin = getattr(mod, "__file__", None) or ""
        if not any(origin.startswith(p) for p in prefixes):
            continue
        values = {}
        for attr, value in vars(mod).items():
            if attr.startswith("__"):
                continue
            if not _plain_data(value):
                skipped.append(f"{name}.{attr}")
                continue
            try:
                blob = pickle.dumps(value, protocol=2)
            except Exception:  # noqa: BLE001
                skipped.append(f"{name}.{attr}")
                continue
            values[attr] = base64.b64encode(blob).decode("ascii")
        modules.append({"name": name, "values": values})

    state = {
        "version": _STATE_VERSION,
        "env_set": env_set,
        "env_del": env_del,
        "cwd": cwd,
        "modules": modules,
        "packages": _installed_packages(),
        "skipped": sorted(skipped),
    }
    max_bytes = int(req.get("max_bytes") or 0)
    if max_bytes and len(json.dumps(state)) > max_bytes:
        return {"ok": False, "reason": "state_too_large"}
    return {"ok": True, "state": state}


def _restore_state(snapshot: dict, req: dict) -> dict:
    """Rehydrate a snapshot (op "restore") into this warm runner. The
    workspace files are ALREADY in place (they ride the manifest-delta
    upload path before this op fires); this re-imports workspace modules
    and overlays their captured globals, then replays env/cwd deltas.
    All-or-nothing per the trust model: a malformed state document is
    refused up front rather than half-applied."""
    import base64
    import importlib

    state = req.get("state")
    if not isinstance(state, dict) or state.get("version") != _STATE_VERSION:
        return {"ok": False, "reason": "bad_state_version"}

    loader = _PlainUnpickler()
    # Decode every blob BEFORE touching interpreter state: a corrupt pickle
    # refuses the whole restore instead of leaving a half-written session.
    decoded = []
    try:
        for entry in state.get("modules") or []:
            values = {
                attr: loader.loads(base64.b64decode(blob))
                for attr, blob in (entry.get("values") or {}).items()
            }
            decoded.append((entry["name"], values))
        env_set = dict(state.get("env_set") or {})
        env_del = list(state.get("env_del") or [])
        cwd = state.get("cwd")
    except Exception:  # noqa: BLE001
        traceback.print_exc()
        return {"ok": False, "reason": "corrupt_state"}

    for k, v in env_set.items():
        os.environ[str(k)] = str(v)
    for k in env_del:
        os.environ.pop(k, None)
    if isinstance(cwd, str) and cwd:
        try:
            os.chdir(cwd)
        except OSError:
            pass

    # During a turn, workspace imports resolve however the user arranged
    # them (sys.path insert, cwd-relative tricks); between turns none of
    # that holds — pin the workspace root for the re-import pass only.
    workspace = snapshot["cwd"]
    added = workspace not in sys.path
    if added:
        sys.path.insert(0, workspace)
    skipped: list[str] = []
    try:
        for name, values in decoded:
            try:
                mod = importlib.import_module(name)
            except Exception:  # noqa: BLE001
                skipped.append(name)
                continue
            for attr, value in values.items():
                try:
                    setattr(mod, attr, value)
                except Exception:  # noqa: BLE001
                    skipped.append(f"{name}.{attr}")
    finally:
        if added:
            try:
                sys.path.remove(workspace)
            except ValueError:
                pass
    return {"ok": True, "skipped": sorted(skipped)}


def _start_server_watchdog() -> None:
    """Die the instant the executor server does — even while the main thread
    is blocked in jax init / jax.distributed rendezvous (where it cannot see
    the request pipe's EOF). POLLHUP on the request pipe fires when the
    server's write end closes; polling without POLLIN steals no request
    bytes from the main loop."""
    import select
    import threading

    def watch() -> None:
        poller = select.poll()
        poller.register(REQ_FD, 0)  # HUP/ERR/NVAL are always reported
        # POLLNVAL: user code closed fd 3 out from under us. The runner can
        # never receive another request, and without exiting on it poll()
        # would return NVAL instantly forever — a 100%-CPU busy spin.
        fatal = select.POLLHUP | select.POLLERR | select.POLLNVAL
        while True:
            for _, event in poller.poll():
                if event & fatal:
                    os._exit(0)

    threading.Thread(target=watch, name="server-watchdog", daemon=True).start()


def main() -> None:
    # Detach stdin; keep stdout/stderr (they reach the executor's log).
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    os.close(devnull)

    _start_server_watchdog()
    _send(_warm_import())
    # Boot snapshot for generation resets — taken AFTER the warm import so
    # anything jax init itself set (TPU env, plugin paths, worker threads)
    # persists and is never misread as user residue.
    import threading

    snapshot = {
        "environ": dict(os.environ),
        "cwd": os.getcwd(),
        "path": list(sys.path),
        "threads": {t.ident for t in threading.enumerate()},
    }

    buf = b""
    while True:
        try:
            chunk = os.read(REQ_FD, 65536)
        except KeyboardInterrupt:
            # The server's cooperative-cancellation SIGINT raced the user
            # code finishing: it landed here, between requests. Dying now
            # would throw away a healthy runner (and its device lease) over
            # a request that already completed — swallow and keep serving.
            continue
        if not chunk:
            # Server is gone; this sandbox is dead. Skip atexit — nothing
            # needs flushing, and jax.distributed's shutdown barrier would
            # block for minutes waiting for peers that are dying too.
            os._exit(0)
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if not line.strip():
                continue
            req = None
            replied = False

            def _reply(obj):
                nonlocal replied
                replied = True
                _send(obj)

            def _reply_error():
                if replied:
                    return
                op = req.get("op") if isinstance(req, dict) else None
                if op in ("reset", "snapshot", "restore"):
                    _reply({"ok": False})
                else:
                    _reply({"exit_code": -2})

            try:
                req = json.loads(line)
                if req.get("op") == "reset":
                    ok = _reset(snapshot)
                    _reply({"ok": ok})
                    if ok:
                        import gc

                        # Post-ack: drop the previous generation's host and
                        # device buffers while the server wipes the
                        # workspace — off the next request's critical path.
                        gc.collect()
                elif req.get("op") == "snapshot":
                    _reply(_snapshot_state(snapshot, req))
                elif req.get("op") == "restore":
                    _reply(_restore_state(snapshot, req))
                elif req.get("op") == "batch":
                    _set_trace_id(req.get("trace_id"))
                    hits_before, misses_before = _cache_counts()
                    reply = _run_batch(req)
                    if _CACHE_LISTENING:
                        hits_after, misses_after = _cache_counts()
                        reply["cache_hits"] = hits_after - hits_before
                        reply["cache_misses"] = misses_after - misses_before
                    _set_trace_id(None)
                    _reply(reply)
                else:
                    _set_trace_id(req.get("trace_id"))
                    hits_before, misses_before = _cache_counts()
                    # Device-memory bracket around the run, only when the
                    # control plane asked (the perf-observer kill switch
                    # keeps the wire — and the sampling cost — untouched).
                    mem_probe = (
                        _DeviceMemoryProbe()
                        if req.get("device_memory")
                        else None
                    )
                    exit_code, violation = _run_one(req)
                    reply = {"exit_code": exit_code}
                    if mem_probe is not None:
                        reply["device_memory"] = mem_probe.finish()
                    if violation:
                        reply["violation"] = violation
                    if _CACHE_LISTENING:
                        hits_after, misses_after = _cache_counts()
                        reply["cache_hits"] = hits_after - hits_before
                        reply["cache_misses"] = misses_after - misses_before
                    _set_trace_id(None)
                    _reply(reply)
            except KeyboardInterrupt:
                # The cancellation SIGINT raced past user code and landed in
                # RUNNER code (dispatch, _send, _run_one's unwind after the
                # handler was restored). The request it aimed at is already
                # over — answer whatever request is in flight (never twice)
                # and keep the process, and its device lease, alive.
                _reply_error()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
                _reply_error()


if __name__ == "__main__":
    main()
