// Resource governance for sandbox executions: typed limit specs (env caps
// clamping per-request overrides), rlimit application for the cold-subprocess
// child, /proc-based process-tree accounting, and the execution watchdog that
// kills a runaway runner group with a TYPED violation instead of letting it
// take the host (and, on shared nodes, its neighbors) down.
//
// Violation kinds (the closed set both halves of the service agree on):
//   oom        — runner-group RSS exceeded its budget (beyond the warm
//                runner's pre-existing baseline)
//   disk_quota — workspace disk usage exceeded its quota
//   nproc      — live descendant-process count exceeded its bound (fork bomb)
//   cpu_time   — cumulative group CPU time exceeded its budget
//   output_cap — a stdout/stderr capture file outgrew the output cap
//
// Env caps (APP_LIMIT_*): operator policy from the sandbox's boot env. They
// are both the default budget and the ceiling — a request's `limits` object
// can only LOWER them (min-clamp), never raise them, so the very snippets the
// guardrail targets cannot turn it off. 0 = that limit is off.

#ifndef EXECUTOR_LIMITS_HPP_
#define EXECUTOR_LIMITS_HPP_

#include <dirent.h>
#include <signal.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include "json.hpp"

namespace limits {

inline const char* kOom = "oom";
inline const char* kDiskQuota = "disk_quota";
inline const char* kNproc = "nproc";
inline const char* kCpuTime = "cpu_time";
inline const char* kOutputCap = "output_cap";

// One execution's effective resource budget. 0 everywhere = ungoverned (the
// pre-governance behavior, and the kill-switch state).
struct LimitSpec {
  long long memory_bytes = 0;  // group RSS beyond the warm baseline
  double cpu_seconds = 0;      // cumulative group CPU beyond the baseline
  long long nproc = 0;         // max live descendant processes
  long long nofile = 0;        // RLIMIT_NOFILE (soft) around user code
  long long fsize_bytes = 0;   // RLIMIT_FSIZE (soft) around user code
  long long disk_bytes = 0;    // workspace disk-usage quota
  long long output_bytes = 0;  // per-stream stdout/stderr capture cap

  bool any() const {
    return memory_bytes > 0 || cpu_seconds > 0 || nproc > 0 || nofile > 0 ||
           fsize_bytes > 0 || disk_bytes > 0 || output_bytes > 0;
  }
};

inline long long env_ll(const char* name) {
  const char* v = getenv(name);
  if (!v || !*v) return 0;
  long long out = atoll(v);
  return out > 0 ? out : 0;
}

inline double env_d(const char* name) {
  const char* v = getenv(name);
  if (!v || !*v) return 0;
  double out = atof(v);
  return out > 0 ? out : 0;
}

// The server's caps-and-defaults, read once at boot.
inline LimitSpec caps_from_env() {
  LimitSpec caps;
  caps.memory_bytes = env_ll("APP_LIMIT_MEMORY_BYTES");
  caps.cpu_seconds = env_d("APP_LIMIT_CPU_SECONDS");
  caps.nproc = env_ll("APP_LIMIT_NPROC");
  caps.nofile = env_ll("APP_LIMIT_NOFILE");
  caps.fsize_bytes = env_ll("APP_LIMIT_FSIZE_BYTES");
  caps.disk_bytes = env_ll("APP_LIMIT_DISK_BYTES");
  // output_bytes is seeded by the caller from APP_MAX_OUTPUT_BYTES (the
  // pre-existing knob), not here — one source of truth for the cap.
  return caps;
}

// Per-request overrides from the /execute body's `limits` object. Unknown
// keys are ignored (wire-compat with future kinds); non-positive values mean
// "no override".
inline LimitSpec from_json(const minijson::Value& v) {
  LimitSpec req;
  if (!v.is_object()) return req;
  long long n;
  if ((n = static_cast<long long>(v.get_number("memory_bytes", 0))) > 0)
    req.memory_bytes = n;
  double c = v.get_number("cpu_seconds", 0);
  if (c > 0) req.cpu_seconds = c;
  if ((n = static_cast<long long>(v.get_number("nproc", 0))) > 0) req.nproc = n;
  if ((n = static_cast<long long>(v.get_number("nofile", 0))) > 0)
    req.nofile = n;
  if ((n = static_cast<long long>(v.get_number("fsize_bytes", 0))) > 0)
    req.fsize_bytes = n;
  if ((n = static_cast<long long>(v.get_number("disk_bytes", 0))) > 0)
    req.disk_bytes = n;
  if ((n = static_cast<long long>(v.get_number("output_bytes", 0))) > 0)
    req.output_bytes = n;
  return req;
}

// Tighten-only merge: where the cap is set, the request may only lower it;
// where the cap is off (0), the request's own bound applies as-is (a client
// may always volunteer a tighter box than the operator demands).
inline long long clamp_ll(long long req, long long cap) {
  if (cap <= 0) return req;
  if (req <= 0) return cap;
  return req < cap ? req : cap;
}

inline double clamp_d(double req, double cap) {
  if (cap <= 0) return req;
  if (req <= 0) return cap;
  return req < cap ? req : cap;
}

inline LimitSpec clamp(const LimitSpec& req, const LimitSpec& caps) {
  LimitSpec eff;
  eff.memory_bytes = clamp_ll(req.memory_bytes, caps.memory_bytes);
  eff.cpu_seconds = clamp_d(req.cpu_seconds, caps.cpu_seconds);
  eff.nproc = clamp_ll(req.nproc, caps.nproc);
  eff.nofile = clamp_ll(req.nofile, caps.nofile);
  eff.fsize_bytes = clamp_ll(req.fsize_bytes, caps.fsize_bytes);
  eff.disk_bytes = clamp_ll(req.disk_bytes, caps.disk_bytes);
  eff.output_bytes = clamp_ll(req.output_bytes, caps.output_bytes);
  return eff;
}

// Applies the setrlimit set in a freshly-forked child, before exec. Soft AND
// hard are set: the cold subprocess is wholly the user's, so unlike the warm
// runner's soft-only window there is no post-run restore to protect.
// RLIMIT_NPROC is best-effort (root bypasses it; the watchdog is the
// enforcement backstop either way).
//
// memory_bytes is deliberately NOT mapped to RLIMIT_AS here: the budget
// means "bytes beyond the baseline" everywhere else (the warm runner's
// rlimit window and the watchdog both subtract one), and an ABSOLUTE
// address-space cap of a realistic extra-window size would kill the cold
// interpreter at import time. Memory in the cold path is the watchdog's
// job (its first sample of the fresh child is the baseline).
//
// SIGXFSZ is set to SIG_IGN — ignored dispositions survive execve — so an
// RLIMIT_FSIZE breach surfaces in user code as a clean OSError(EFBIG),
// exactly like the warm runner's handling, instead of an unexplained
// signal death.
inline void apply_child_rlimits(const LimitSpec& spec) {
  auto set = [](int which, rlim_t value) {
    struct rlimit rl;
    if (getrlimit(which, &rl) != 0) return;
    if (rl.rlim_max != RLIM_INFINITY && value > rl.rlim_max)
      value = rl.rlim_max;
    rl.rlim_cur = value;
    if (rl.rlim_max == RLIM_INFINITY || value > rl.rlim_max) rl.rlim_max = value;
    setrlimit(which, &rl);
  };
  // Soft-only lowerer for RLIMIT_CPU: the kernel SIGKILLs at the HARD cpu
  // limit but sends the catchable/classifiable SIGXCPU at the soft one —
  // collapsing hard onto soft would turn every cold-path CPU breach into
  // an untyped exit-137 instead of the 128+SIGXCPU the server classifies
  // as cpu_time.
  auto lower_soft = [](int which, rlim_t value) {
    struct rlimit rl;
    if (getrlimit(which, &rl) != 0) return;
    if (rl.rlim_max != RLIM_INFINITY && value > rl.rlim_max)
      value = rl.rlim_max;
    if (rl.rlim_cur == RLIM_INFINITY || value < rl.rlim_cur) {
      rl.rlim_cur = value;
      setrlimit(which, &rl);
    }
  };
  if (spec.cpu_seconds > 0)
    lower_soft(RLIMIT_CPU, static_cast<rlim_t>(spec.cpu_seconds + 0.999));
  if (spec.nproc > 0) set(RLIMIT_NPROC, static_cast<rlim_t>(spec.nproc));
  if (spec.nofile > 0) set(RLIMIT_NOFILE, static_cast<rlim_t>(spec.nofile));
  if (spec.fsize_bytes > 0) {
    signal(SIGXFSZ, SIG_IGN);
    set(RLIMIT_FSIZE, static_cast<rlim_t>(spec.fsize_bytes));
  }
}

// ---------------------------------------------------------------------------
// /proc process-tree accounting.

struct TreeStats {
  long long rss_bytes = 0;  // whole tree, root included
  double cpu_seconds = 0;   // utime+stime of live members + root's reaped
                            // children (cutime/cstime) — a fork bomb's dead
                            // generations still count
  int descendants = 0;      // live processes under root (root excluded)
};

// One pass over /proc: parent map + per-pid (rss, cpu, reaped-child cpu).
// Returns false when /proc is unreadable (stats stay zero — the watchdog
// then simply cannot see, it never false-positives).
inline bool sample_tree(pid_t root, TreeStats& out) {
  DIR* d = opendir("/proc");
  if (!d) return false;
  struct Row {
    pid_t ppid;
    long long rss;
    double cpu;
    double reaped_cpu;
  };
  std::map<pid_t, Row> rows;
  long page = sysconf(_SC_PAGESIZE);
  long hz = sysconf(_SC_CLK_TCK);
  if (hz <= 0) hz = 100;
  while (dirent* e = readdir(d)) {
    if (e->d_name[0] < '0' || e->d_name[0] > '9') continue;
    pid_t pid = static_cast<pid_t>(atoi(e->d_name));
    char path[64];
    snprintf(path, sizeof(path), "/proc/%d/stat", pid);
    FILE* f = fopen(path, "r");
    if (!f) continue;
    char buf[1024];
    size_t n = fread(buf, 1, sizeof(buf) - 1, f);
    fclose(f);
    if (n == 0) continue;
    buf[n] = 0;
    // Fields after the parenthesized comm (which may contain spaces):
    // state ppid pgrp session tty tpgid flags minflt cminflt majflt cmajflt
    // utime stime cutime cstime ... (22) rss
    char* close_paren = strrchr(buf, ')');
    if (!close_paren) continue;
    const char* rest = close_paren + 1;
    char state;
    long ppid;
    unsigned long long utime, stime;
    long long cutime, cstime;
    unsigned long long skip_u;
    long long rss_pages = 0;
    // state(1) ppid(2) pgrp session tty tpgid flags minflt cminflt majflt
    // cmajflt utime(12) stime(13) cutime(14) cstime(15) priority nice
    // num_threads itrealvalue starttime vsize(21) rss(22)
    int matched = sscanf(
        rest,
        " %c %ld %*d %*d %*d %*d %*u %*u %*u %*u %*u %llu %llu %lld %lld "
        "%*d %*d %*d %*d %*u %llu %lld",
        &state, &ppid, &utime, &stime, &cutime, &cstime, &skip_u, &rss_pages);
    if (matched < 8) continue;
    rows[pid] = Row{static_cast<pid_t>(ppid),
                    rss_pages * static_cast<long long>(page),
                    static_cast<double>(utime + stime) / hz,
                    static_cast<double>(cutime + cstime) / hz};
  }
  closedir(d);
  auto root_row = rows.find(root);
  if (root_row == rows.end()) return false;
  std::map<pid_t, std::vector<pid_t>> children;
  for (const auto& [pid, row] : rows) children[row.ppid].push_back(pid);
  std::vector<pid_t> stack = {root};
  bool first = true;
  while (!stack.empty()) {
    pid_t pid = stack.back();
    stack.pop_back();
    const Row& row = rows[pid];
    out.rss_bytes += row.rss;
    out.cpu_seconds += row.cpu + row.reaped_cpu;
    if (!first) out.descendants += 1;
    first = false;
    auto it = children.find(pid);
    if (it != children.end())
      for (pid_t child : it->second) stack.push_back(child);
  }
  return true;
}

// Recursive workspace disk usage (allocated blocks, not nominal size — a
// sparse-file trick must not count as a quota breach the kernel never paid
// for). Symlinks are lstat'ed, never followed.
inline long long dir_usage_bytes(const std::string& base) {
  long long total = 0;
  std::vector<std::string> stack = {base};
  while (!stack.empty()) {
    std::string dir = stack.back();
    stack.pop_back();
    DIR* d = opendir(dir.c_str());
    if (!d) continue;
    while (dirent* e = readdir(d)) {
      std::string name = e->d_name;
      if (name == "." || name == "..") continue;
      std::string full = dir + "/" + name;
      struct stat st;
      if (lstat(full.c_str(), &st) != 0) continue;
      total += static_cast<long long>(st.st_blocks) * 512;
      if (S_ISDIR(st.st_mode)) stack.push_back(full);
    }
    closedir(d);
  }
  return total;
}

// ---------------------------------------------------------------------------
// The execution watchdog: a sampling thread that enforces the spec against a
// live runner group and kills the WHOLE group (SIGKILL to the session/pgid)
// on the first breach, recording which limit fired. Baselines (the warm
// runner's own RSS/CPU, jax included) are subtracted so the budget governs
// only what THIS execution added.

class Watchdog {
 public:
  Watchdog(LimitSpec spec, pid_t group_leader, std::string workspace,
           std::vector<std::string> capture_paths, double interval_s)
      : spec_(spec),
        leader_(group_leader),
        workspace_(std::move(workspace)),
        capture_paths_(std::move(capture_paths)),
        interval_s_(interval_s > 0 ? interval_s : 0.1) {
    // Baseline only when a tree-watching limit is armed: an ungoverned
    // request must not pay a /proc scan just for constructing the (inert)
    // watchdog on its stack.
    TreeStats base;
    if (group_leader > 0 &&
        (spec_.memory_bytes > 0 || spec_.nproc > 0 || spec_.cpu_seconds > 0) &&
        sample_tree(group_leader, base)) {
      rss_baseline_ = base.rss_bytes;
      cpu_baseline_ = base.cpu_seconds;
      baseline_ready_ = true;
    }
  }

  ~Watchdog() { stop(); }

  // Late leader binding for the cold-subprocess path: the child pid only
  // exists after run_subprocess forks, while the watchdog thread must
  // already be running (the fork happens inside a blocking call). A fresh
  // child has no meaningful baseline — the first sample serves as one.
  void set_leader(pid_t leader) { leader_.store(leader); }

  bool watches_anything() const {
    return spec_.memory_bytes > 0 || spec_.nproc > 0 || spec_.cpu_seconds > 0 ||
           spec_.disk_bytes > 0 || spec_.output_bytes > 0;
  }

  void start() {
    if (!watches_anything() || running_.load()) return;
    running_.store(true);
    thread_ = std::thread([this] { run(); });
  }

  void stop() {
    running_.store(false);
    if (thread_.joinable()) thread_.join();
  }

  // The kind that fired, or "" when the run stayed inside its box.
  std::string violation() const {
    const char* kind = violation_.load();
    return kind ? std::string(kind) : std::string();
  }

 private:
  // Lock-free on purpose: one Watchdog lives on the request-handler's
  // stack per execute, and TSan cannot see a trivially-destructed
  // std::mutex die — sequential requests reusing the same stack slot read
  // as mutex misuse. Atomics + a short sleep tick sidestep the whole
  // class of problem; stop() latency is bounded by one 10 ms tick.
  void run() {
    const double tick_s = 0.01;
    double since_check = interval_s_;  // first check happens immediately
    while (running_.load()) {
      if (since_check + 1e-9 >= interval_s_) {
        since_check = 0;
        const char* kind = check_once();
        if (kind) {
          violation_.store(kind);
          // A breach can land before the cold child exists (disk/output
          // checks run leaderless pre-fork): park until the leader binds
          // so the verdict is enforced, not just recorded — an
          // unsupervised run labeled "violation" would be a lie.
          while (running_.load()) {
            pid_t leader = leader_.load();
            if (leader > 0) {
              kill(-leader, SIGKILL);
              return;  // one breach is terminal; the group is dead
            }
            usleep(static_cast<useconds_t>(tick_s * 1e6));
          }
          return;
        }
      }
      usleep(static_cast<useconds_t>(tick_s * 1e6));
      since_check += tick_s;
    }
  }

  const char* check_once() {
    pid_t leader = leader_.load();
    if (leader > 0 &&
        (spec_.memory_bytes > 0 || spec_.nproc > 0 || spec_.cpu_seconds > 0)) {
      TreeStats now;
      if (sample_tree(leader, now)) {
        if (!baseline_ready_) {
          rss_baseline_ = now.rss_bytes;
          cpu_baseline_ = now.cpu_seconds;
          baseline_ready_ = true;
        }
        // Same layering as CPU: the runner's in-process rlimit window
        // fires at the budget with a clean MemoryError; the watchdog's
        // threshold carries slack so it only kills when user code dodged
        // the soft layer (raised its own rlimit, native allocs, children).
        if (spec_.memory_bytes > 0 &&
            now.rss_bytes - rss_baseline_ >
                spec_.memory_bytes + mem_slack(spec_.memory_bytes))
          return kOom;
        if (spec_.nproc > 0 && now.descendants > spec_.nproc) return kNproc;
        // The in-process soft-CPU guard (runner.py SIGXCPU) and the cold
        // child's RLIMIT_CPU fire first and report cleanly; the watchdog's
        // threshold carries slack so it only acts when user code dodged
        // them (native spin, masked signals).
        if (spec_.cpu_seconds > 0 &&
            now.cpu_seconds - cpu_baseline_ >
                spec_.cpu_seconds + cpu_slack(spec_.cpu_seconds))
          return kCpuTime;
      }
    }
    if (spec_.disk_bytes > 0 && ++disk_countdown_ >= disk_check_every()) {
      // The disk check is a full recursive walk — throttle it to ~4 Hz
      // even when the tree-stat cadence is tighter (the post-exec scan
      // catches anything a coarser cadence misses).
      disk_countdown_ = 0;
      if (dir_usage_bytes(workspace_) > spec_.disk_bytes) return kDiskQuota;
    }
    if (spec_.output_bytes > 0) {
      for (const auto& path : capture_paths_) {
        struct stat st;
        if (stat(path.c_str(), &st) == 0 &&
            static_cast<long long>(st.st_size) > spec_.output_bytes)
          return kOutputCap;
      }
    }
    return nullptr;
  }

  static double cpu_slack(double budget) {
    double slack = budget * 0.5;
    return slack > 2.0 ? slack : 2.0;
  }

  static long long mem_slack(long long budget) {
    long long slack = budget / 2;
    const long long floor = 32LL << 20;
    return slack > floor ? slack : floor;
  }

  int disk_check_every() const {
    int every = static_cast<int>(0.25 / interval_s_);
    return every > 1 ? every : 1;
  }

  LimitSpec spec_;
  std::atomic<pid_t> leader_;
  std::string workspace_;
  std::vector<std::string> capture_paths_;
  double interval_s_;
  long long rss_baseline_ = 0;
  double cpu_baseline_ = 0;
  bool baseline_ready_ = false;
  int disk_countdown_ = 1 << 20;  // first armed check runs immediately
  std::atomic<bool> running_{false};
  std::atomic<const char*> violation_{nullptr};
  std::thread thread_;
};

}  // namespace limits

#endif  // EXECUTOR_LIMITS_HPP_
