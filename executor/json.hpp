// Minimal JSON parser/emitter for the in-sandbox executor server.
// No external dependencies; supports the full JSON grammar (objects, arrays,
// strings with \uXXXX escapes, numbers, bools, null) — enough for the
// /execute request/response protocol and the runner wire format.
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace minijson {

class Value;
using Object = std::map<std::string, Value>;
using Array = std::vector<Value>;

class Value {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(double d) : type_(Type::Number), num_(d) {}
  Value(int i) : type_(Type::Number), num_(i) {}
  Value(int64_t i) : type_(Type::Number), num_(static_cast<double>(i)) {}
  Value(const char* s) : type_(Type::String), str_(s) {}
  Value(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Value(Array a) : type_(Type::Array), arr_(std::move(a)) {}
  Value(Object o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool() const { check(Type::Bool); return bool_; }
  double as_number() const { check(Type::Number); return num_; }
  const std::string& as_string() const { check(Type::String); return str_; }
  const Array& as_array() const { check(Type::Array); return arr_; }
  const Object& as_object() const { check(Type::Object); return obj_; }
  Object& as_object() { check(Type::Object); return obj_; }

  // Object convenience: returns Null value for missing keys.
  const Value& get(const std::string& key) const {
    static const Value null_value;
    if (type_ != Type::Object) return null_value;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_value : it->second;
  }

  std::string get_string(const std::string& key, const std::string& dflt = "") const {
    const Value& v = get(key);
    return v.is_string() ? v.as_string() : dflt;
  }
  double get_number(const std::string& key, double dflt = 0) const {
    const Value& v = get(key);
    return v.is_number() ? v.as_number() : dflt;
  }
  bool get_bool(const std::string& key, bool dflt = false) const {
    const Value& v = get(key);
    return v.is_bool() ? v.as_bool() : dflt;
  }

  std::string dump() const {
    std::string out;
    dump_to(out);
    return out;
  }

 private:
  void check(Type t) const {
    if (type_ != t) throw std::runtime_error("minijson: wrong type access");
  }

  static void escape_to(const std::string& s, std::string& out) {
    out += '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\b': out += "\\b"; break;
        case '\f': out += "\\f"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += static_cast<char>(c);
          }
      }
    }
    out += '"';
  }

  void dump_to(std::string& out) const {
    switch (type_) {
      case Type::Null: out += "null"; break;
      case Type::Bool: out += bool_ ? "true" : "false"; break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == static_cast<int64_t>(num_)) {
          out += std::to_string(static_cast<int64_t>(num_));
        } else {
          char buf[32];
          snprintf(buf, sizeof(buf), "%.17g", num_);
          out += buf;
        }
        break;
      }
      case Type::String: escape_to(str_, out); break;
      case Type::Array: {
        out += '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) out += ',';
          arr_[i].dump_to(out);
        }
        out += ']';
        break;
      }
      case Type::Object: {
        out += '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) out += ',';
          first = false;
          escape_to(k, out);
          out += ':';
          v.dump_to(out);
        }
        out += '}';
        break;
      }
    }
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  Array arr_;
  Object obj_;
};

class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  Value parse() {
    Value v = parse_value();
    skip_ws();
    if (pos_ != s_.size()) throw std::runtime_error("minijson: trailing data");
    return v;
  }

 private:
  void skip_ws() {
    while (pos_ < s_.size() &&
           (s_[pos_] == ' ' || s_[pos_] == '\t' || s_[pos_] == '\n' || s_[pos_] == '\r'))
      ++pos_;
  }

  char peek() {
    skip_ws();
    if (pos_ >= s_.size()) throw std::runtime_error("minijson: unexpected end");
    return s_[pos_];
  }

  void expect(char c) {
    if (peek() != c) throw std::runtime_error(std::string("minijson: expected ") + c);
    ++pos_;
  }

  bool consume(char c) {
    if (pos_ < s_.size() && peek() == c) {
      ++pos_;
      return true;
    }
    return false;
  }

  Value parse_value() {
    char c = peek();
    switch (c) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return Value(parse_string());
      case 't': literal("true"); return Value(true);
      case 'f': literal("false"); return Value(false);
      case 'n': literal("null"); return Value(nullptr);
      default: return parse_number();
    }
  }

  void literal(const char* lit) {
    size_t n = strlen(lit);
    if (s_.compare(pos_, n, lit) != 0) throw std::runtime_error("minijson: bad literal");
    pos_ += n;
  }

  Value parse_object() {
    expect('{');
    Object obj;
    if (consume('}')) return Value(std::move(obj));
    while (true) {
      std::string key = parse_string_at();
      expect(':');
      obj[key] = parse_value();
      if (consume('}')) break;
      expect(',');
    }
    return Value(std::move(obj));
  }

  Value parse_array() {
    expect('[');
    Array arr;
    if (consume(']')) return Value(std::move(arr));
    while (true) {
      arr.push_back(parse_value());
      if (consume(']')) break;
      expect(',');
    }
    return Value(std::move(arr));
  }

  std::string parse_string_at() {
    if (peek() != '"') throw std::runtime_error("minijson: expected string");
    return parse_string();
  }

  static void utf8_append(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  uint32_t parse_hex4() {
    if (pos_ + 4 > s_.size()) throw std::runtime_error("minijson: bad \\u escape");
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = s_[pos_++];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else throw std::runtime_error("minijson: bad hex digit");
    }
    return v;
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      if (pos_ >= s_.size()) throw std::runtime_error("minijson: unterminated string");
      char c = s_[pos_++];
      if (c == '"') break;
      if (c == '\\') {
        if (pos_ >= s_.size()) throw std::runtime_error("minijson: bad escape");
        char e = s_[pos_++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            uint32_t cp = parse_hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              // surrogate pair
              if (pos_ + 1 < s_.size() && s_[pos_] == '\\' && s_[pos_ + 1] == 'u') {
                pos_ += 2;
                uint32_t lo = parse_hex4();
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              }
            }
            utf8_append(out, cp);
            break;
          }
          default: throw std::runtime_error("minijson: bad escape char");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  Value parse_number() {
    size_t start = pos_;
    if (pos_ < s_.size() && s_[pos_] == '-') ++pos_;
    while (pos_ < s_.size() &&
           (isdigit(static_cast<unsigned char>(s_[pos_])) || s_[pos_] == '.' ||
            s_[pos_] == 'e' || s_[pos_] == 'E' || s_[pos_] == '+' || s_[pos_] == '-'))
      ++pos_;
    if (pos_ == start) throw std::runtime_error("minijson: bad number");
    return Value(std::stod(s_.substr(start, pos_ - start)));
  }

  const std::string& s_;
  size_t pos_ = 0;
};

inline Value parse(const std::string& text) { return Parser(text).parse(); }

}  // namespace minijson
