"""Sandbox-wide import patches, auto-loaded into every user Python process.

Installed into the sandbox venv's site-packages (reference parity:
executor/sitecustomize.py via executor/Dockerfile:107). Patches are applied
lazily via an import hook so non-matching code pays ~nothing:

- matplotlib.pyplot.show() → savefig("plot.png") (headless sandbox)
- PIL.ImageShow.show() → img.save("image.png")
- json → datetime/date-aware default encoder + ISO-parsing decoder
- numpy → the TPU dispatch shim (bee_code_interpreter_fs_tpu.ops.npdispatch),
  when APP_NUMPY_DISPATCH=1: user-submitted array code transparently runs on
  XLA/TPU (the north-star hook point, SURVEY.md §2.15).
"""

import builtins
import os
import sys

# Python imports exactly one `sitecustomize` module; if the host platform
# ships its own (e.g. a PJRT plugin registration shim) further down sys.path,
# chain-load it FIRST — plugin registration must precede any jax import below.
def _chain_shadowed_sitecustomize() -> None:
    import importlib.util

    my_file = os.path.realpath(__file__)
    for entry in sys.path:
        if not entry:
            continue
        candidate = os.path.join(entry, "sitecustomize.py")
        # realpath both sides: a symlink alias of this dir must not make us
        # exec ourselves recursively.
        if os.path.exists(candidate) and os.path.realpath(candidate) != my_file:
            try:
                spec = importlib.util.spec_from_file_location(
                    "_chained_sitecustomize", candidate
                )
                module = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(module)
            except Exception:  # noqa: BLE001 — platform shim is best-effort
                import traceback

                traceback.print_exc()
            break


_chain_shadowed_sitecustomize()

_PATCHED: set[str] = set()


def _patch_matplotlib_pyplot(plt) -> None:
    def _show(*args, **kwargs):  # noqa: ANN002, ANN003
        try:
            plt.savefig("plot.png")
        finally:
            plt.close("all")

    plt.show = _show


def _patch_pil_imageshow(imageshow) -> None:
    def _show(image, title=None, **options):  # noqa: ANN001, ANN003
        image.save("image.png")
        return True

    imageshow.show = _show


def _patch_moviepy(module) -> None:
    """Force quiet, loggerless video writes: moviepy's progress bars flood
    the captured stdout that Execute returns to the client. Keyed on both
    the 1.x (`moviepy.editor`, has a `verbose` kwarg) and 2.x (`moviepy`,
    logger-only) module layouts; the signature decides what to force."""
    import inspect

    clip_cls = getattr(module, "VideoClip", None)
    if clip_cls is None or not hasattr(clip_cls, "write_videofile"):
        return
    original = clip_cls.write_videofile
    try:
        has_verbose = "verbose" in inspect.signature(original).parameters
    except (TypeError, ValueError):
        has_verbose = False

    def write_videofile(self, *args, **kwargs):  # noqa: ANN001, ANN002, ANN003
        if has_verbose:
            kwargs["verbose"] = False
        kwargs["logger"] = None
        return original(self, *args, **kwargs)

    clip_cls.write_videofile = write_videofile


def _patch_json(json_mod) -> None:
    import datetime

    _default_encoder = json_mod.JSONEncoder

    class DateTimeEncoder(_default_encoder):
        def default(self, o):  # noqa: ANN001
            if isinstance(o, (datetime.datetime, datetime.date, datetime.time)):
                return o.isoformat()
            return super().default(o)

    _orig_dumps = json_mod.dumps
    _orig_dump = json_mod.dump

    def dumps(*args, **kwargs):  # noqa: ANN002, ANN003
        kwargs.setdefault("cls", DateTimeEncoder)
        return _orig_dumps(*args, **kwargs)

    def dump(*args, **kwargs):  # noqa: ANN002, ANN003
        kwargs.setdefault("cls", DateTimeEncoder)
        return _orig_dump(*args, **kwargs)

    json_mod.dumps = dumps
    json_mod.dump = dump
    json_mod.DateTimeEncoder = DateTimeEncoder


def _patch_jax_profile(jax_mod) -> None:
    """APP_JAX_PROFILE=1 (cold-subprocess path; the warm runner handles this
    itself): start a profiler trace at first jax import, stop + zip it to
    ./profile.zip at exit so the changed-file scan ships it back."""
    if str(os.environ.get("APP_JAX_PROFILE", "")).lower() in ("", "0", "false"):
        return
    import atexit

    import jax_profile  # deployed alongside this file

    trace_dir = jax_profile.start_trace()

    def _finish() -> None:
        try:
            jax_profile.finish_trace(trace_dir)
        except Exception:  # noqa: BLE001 — profiling is best-effort
            pass

    atexit.register(_finish)


_PATCHES = {
    "matplotlib.pyplot": _patch_matplotlib_pyplot,
    "PIL.ImageShow": _patch_pil_imageshow,
    "moviepy.editor": _patch_moviepy,  # moviepy 1.x
    "moviepy": _patch_moviepy,  # moviepy 2.x (flat layout)
    "json": _patch_json,
    "jax": _patch_jax_profile,
}

_orig_import = builtins.__import__


def _patched_import(name, globals=None, locals=None, fromlist=(), level=0):  # noqa: A002
    module = _orig_import(name, globals, locals, fromlist, level)
    for mod_name, patch in _PATCHES.items():
        if mod_name in sys.modules and mod_name not in _PATCHED:
            target = sys.modules[mod_name]
            # The hook also fires on imports nested inside mod_name's own
            # __init__ (where the module exists in sys.modules but is only
            # partially initialized — e.g. jax has no `profiler` attr yet).
            # Defer until the module finishes importing.
            spec = getattr(target, "__spec__", None)
            if spec is not None and getattr(spec, "_initializing", False):
                continue
            _PATCHED.add(mod_name)
            try:
                patch(target)
            except Exception:  # noqa: BLE001 — patches are best-effort
                pass
    return module


builtins.__import__ = _patched_import

if os.environ.get("APP_NUMPY_DISPATCH", "0") not in ("0", "false", ""):
    try:
        from bee_code_interpreter_fs_tpu.ops.npdispatch import install as _install_np

        _install_np()
    except Exception:  # noqa: BLE001 — fall back to stock numpy
        import traceback

        sys.stderr.write("[sitecustomize] numpy dispatch install failed:\n")
        traceback.print_exc()
