"""Shell-syntax fallback for user scripts that mix Python and shell lines.

LLM-emitted snippets routinely interleave shell commands with Python — the
reference runs everything under xonsh for exactly this reason
(/root/reference/executor/server.rs:197-207). xonsh costs ~80 ms of startup
per execution (server.rs:204); this module recovers the same mixed-snippet
tolerance as a zero-cost source transform instead:

1. SyntaxError repair loop: lines that don't parse as Python but look like
   commands (``pip install requests``, ``echo hi > out.txt``) are rewritten
   to ``__shell__('<line>')`` and the compile is retried, until the script
   parses or a non-shell-ish error remains (which is then surfaced
   untouched).
2. Undefined-command statements: a bare ``ls`` IS valid Python (a Name
   expression) that would die with NameError at runtime. An AST pass
   rewrites top-level expression statements made of names never defined in
   the script (including ``ls | grep foo`` pipe chains) into shell calls —
   the same auto-recovery tradeoff xonsh makes.

``__shell__`` is injected via builtins (never prepended to the source), so
line numbers in user tracebacks stay exact. Scripts that are pure Python
compile on the first try and pay one ``compile()`` — no interpreter swap,
no startup tax.

Contract vs xonsh (documented divergences — VERDICT r2 #8). Covered:
bare commands; pipes/redirection/&&/|| within a shell line (delegated to
``sh``); ``!``-escapes; ``cd`` / ``export`` persisting across lines and into
the surrounding Python (os.chdir / os.environ); ``$VAR`` expansion inside
shell lines, including the ``cd``/``export`` fast paths (environment =
process env + prior ``export``s; single-quoted export values stay literal,
shell-style). NOT covered — these stay ordinary Python or fail loudly
rather than half-working:
  * ``$VAR`` inside *Python* expressions (xonsh: ``print($HOME)``) — here
    that is a real NameError; use ``os.environ``.
  * Python-expression substitution inside shell lines (xonsh ``@(expr)``).
  * Capturing shell output into Python variables (xonsh ``x = $(cmd)``) —
    a line that parses as Python is never treated as shell; use
    ``subprocess``.
  * xonsh globbing/regex paths (`` `re` ``) and its alias system.
  * Per-line subshells: unlike xonsh's single session, each rewritten line
    is its own ``sh -c`` (except ``cd``/``export``, persisted explicitly) —
    ``set -e``-style abort semantics across lines do not exist; a failing
    line reports and the next line runs (plain shell-script behavior).
"""

from __future__ import annotations

import ast
import builtins
import keyword
import re

MAX_FIXES = 200

# First token of a line that may be treated as a shell command. Anything
# starting with a Python keyword stays Python (it is broken Python, and the
# user deserves the real SyntaxError).
_CMD_TOKEN = re.compile(r"^[A-Za-z0-9_.~/-]+")


def _shellish(stripped: str) -> bool:
    if stripped.startswith("!"):  # IPython-style explicit shell escape
        return True
    match = _CMD_TOKEN.match(stripped)
    if not match:
        return False
    first = match.group(0)
    if keyword.iskeyword(first):
        return False
    return True


_CD_LINE = re.compile(r"^cd(?:\s+(?P<path>\S+))?\s*$")
_EXPORT_LINE = re.compile(r"^export\s+(?P<name>[A-Za-z_][A-Za-z0-9_]*)=(?P<value>.*)$")
_ENV_REF = re.compile(r"\$(?:\{(?P<braced>[A-Za-z_][A-Za-z0-9_]*)\}|(?P<name>[A-Za-z_][A-Za-z0-9_]*))")


def _expand_env(text: str) -> str:
    """$VAR / ${VAR} expansion with sh semantics: UNDEFINED variables expand
    to empty (os.path.expandvars would leave the literal '$VAR', making the
    same reference mean different things on a cd/export line vs any other
    shell line, which the subshell expands)."""
    import os

    return _ENV_REF.sub(
        lambda m: os.environ.get(m.group("braced") or m.group("name"), ""), text
    )


def run_shell_line(cmd: str) -> int:
    """Execute one shell line; inherits cwd/env/stdout/stderr. Mirrors shell
    script semantics (no set -e): a failing command reports via stderr and
    the next line still runs.

    ``cd <dir>`` and ``export K=V`` as standalone lines mutate the PYTHON
    process (os.chdir / os.environ) — under xonsh those persist across lines
    and into the surrounding Python, and each line here is otherwise its own
    subprocess whose state would vanish. Compound commands (``cd x && make``)
    stay in one subprocess, where the shell scopes them itself."""
    import os
    import subprocess
    import sys

    cd = _CD_LINE.match(cmd.strip())
    if cd:
        # $VAR expands from the live environment (prior `export`s included),
        # matching what the subshell does for any other command line —
        # including empty expansion of undefined names.
        target = os.path.expanduser(_expand_env(cd.group("path") or "~"))
        try:
            os.chdir(target)
            return 0
        except OSError as e:
            print(f"cd: {target}: {e.strerror}", file=sys.stderr)
            return 1
    export = _EXPORT_LINE.match(cmd.strip())
    if export:
        value = export.group("value").strip()
        if len(value) >= 2 and value[0] == value[-1] == "'":
            value = value[1:-1]  # single quotes: literal, shell-style
        else:
            if len(value) >= 2 and value[0] == value[-1] == '"':
                value = value[1:-1]
            value = _expand_env(value)
        os.environ[export.group("name")] = value
        return 0
    return subprocess.run(cmd, shell=True).returncode


def install_shell_builtin() -> None:
    builtins.__shell__ = run_shell_line


def _line_replace(lines: list[str], lineno: int, command: str) -> None:
    line = lines[lineno - 1]
    indent = line[: len(line) - len(line.lstrip())]
    lines[lineno - 1] = f"{indent}__shell__({command!r})"


def _fix_syntax_lines(source: str) -> tuple[str, bool]:
    """Repair loop over SyntaxErrors; returns (source, fully_parses)."""
    lines = source.split("\n")
    touched: set[int] = set()
    for _ in range(MAX_FIXES):
        candidate = "\n".join(lines)
        try:
            compile(candidate, "<fallback-check>", "exec")
            return candidate, True
        except SyntaxError as e:
            lineno = e.lineno
            if (
                lineno is None
                or not 1 <= lineno <= len(lines)
                or lineno in touched
            ):
                return source, False
            stripped = lines[lineno - 1].strip()
            # A ';' means Python and shell may share the line ('x = 1; echo
            # hi') — whole-line replacement would swallow the Python part.
            # Surface the original error instead of guessing.
            if not stripped or ";" in stripped or not _shellish(stripped):
                return source, False
            touched.add(lineno)
            _line_replace(lines, lineno, stripped.lstrip("!").strip())
        except ValueError:
            return source, False
    return source, False


def _defined_names(tree: ast.Module) -> set[str]:
    """Every name the script itself binds, anywhere (conservative scope)."""
    defined: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            defined.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            defined.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                defined.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.arg):
            defined.add(node.arg)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            defined.update(node.names)
    return defined


def _is_command_expr(value: ast.expr, defined: set[str]) -> bool:
    """True for expressions that can only be shell commands: bare undefined
    names and ``|``-chains of them (``ls``, ``ls | grep foo``)."""
    if isinstance(value, ast.Name):
        return value.id not in defined and not hasattr(builtins, value.id)
    if isinstance(value, ast.BinOp) and isinstance(value.op, ast.BitOr):
        # Every leaf must be an undefined name (`ls | wc`): a chain with any
        # defined operand is much more likely real Python with a typo, and
        # the honest NameError beats a mystifying `sh: not found`.
        return _is_command_expr(value.left, defined) and _is_command_expr(
            value.right, defined
        )
    return False


def _fix_undefined_commands(source: str) -> str:
    """Rewrite single-line expression statements of undefined names into
    shell calls (module top level and inside simple blocks)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:  # pragma: no cover — caller ensured it parses
        return source
    defined = _defined_names(tree)
    lines = source.split("\n")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Expr):
            continue
        if node.lineno != node.end_lineno:  # multi-line: leave alone
            continue
        if _is_command_expr(node.value, defined):
            segment = ast.get_source_segment(source, node)
            # Only when the statement IS the whole line: 'x = 1; ls' must
            # not lose the assignment to a whole-line rewrite.
            if segment and segment.strip() == lines[node.lineno - 1].strip():
                _line_replace(lines, node.lineno, segment.strip())
    return "\n".join(lines)


def transform(source: str) -> tuple[str, bool]:
    """Returns (runnable_source, changed). Pure-Python sources come back
    untouched after one compile(); unfixable sources come back untouched so
    the user sees the original SyntaxError."""
    fixed, parses = _fix_syntax_lines(source)
    if not parses:
        return source, False
    result = _fix_undefined_commands(fixed)
    return result, result != source


def prepare(source_path: str) -> str:
    """Transform the script at source_path if it needs shell fallback;
    returns the path to run (a sibling temp file when transformed). Installs
    the ``__shell__`` builtin either way — cheap, and keeps behavior
    identical whether or not a fallback happened."""
    install_shell_builtin()
    try:
        with open(source_path, encoding="utf-8", errors="replace") as f:
            source = f.read()
    except OSError:
        return source_path
    transformed, changed = transform(source)
    if not changed:
        return source_path
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".py", prefix="shellfb-")
    with open(fd, "w", encoding="utf-8") as f:
        f.write(transformed)
    return path
