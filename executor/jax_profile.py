"""Shared JAX profiler trace capture, used by both execution paths:

- executor/runner.py (warm in-process path) wraps each profiled run directly;
- executor/sitecustomize.py (cold subprocess path) starts a trace at first
  jax import and finishes it atexit.

Deployed next to both importers (the executor/ dir locally; the sandbox
image installs it into site-packages alongside sitecustomize.py).
"""

import os
import shutil
import tempfile
import zipfile

PROFILE_ZIP = "profile.zip"


def start_trace() -> str:
    """Begin a JAX profiler trace into a scratch dir; returns the dir."""
    import jax

    trace_dir = tempfile.mkdtemp(prefix="jax-profile-")
    jax.profiler.start_trace(trace_dir)
    return trace_dir


def finish_trace(trace_dir: str, dest: str = PROFILE_ZIP) -> None:
    """Stop the trace and zip it to ``dest`` (relative to cwd, which both
    execution paths set to the workspace — so the changed-file scan ships
    the zip back to the client)."""
    import jax

    try:
        jax.profiler.stop_trace()
        with zipfile.ZipFile(dest, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, names in os.walk(trace_dir):
                for name in names:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, trace_dir))
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)
