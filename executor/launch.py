"""Cold-path script launcher: shell-fallback preprocessing + runpy.

The warm runner (runner.py) applies the same shellfb.prepare() in-process;
this launcher gives the cold-subprocess path (warm runner off or restarting)
identical mixed-Python/shell semantics: `python launch.py <script> [argv...]`.
"""

import runpy
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import shellfb  # noqa: E402

del sys.path[0]


def main() -> None:
    source_path = sys.argv[1]
    run_path = shellfb.prepare(source_path)
    # argv as the script would see it when run directly
    sys.argv = [source_path] + sys.argv[2:]
    try:
        runpy.run_path(run_path, run_name="__main__")
    finally:
        if run_path != source_path:
            Path(run_path).unlink(missing_ok=True)


if __name__ == "__main__":
    main()
